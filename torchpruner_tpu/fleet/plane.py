"""The transport-agnostic request plane: durable, redrivable records.

The single-replica engine's :class:`~torchpruner_tpu.serve.scheduler.
Scheduler` tracks requests in process memory — a ``kill -9`` loses every
queued and in-flight request with it.  The request plane is the fleet's
answer: one :class:`PlaneRecord` per ACCEPTED request (wire payload,
deadline, attempt count, assignment, outcome) in an atomic JSON journal
(the ``resilience.manifest.atomic_write_json`` discipline), flushed
BEFORE the acceptance is acknowledged.  That makes the core robustness
contract structural rather than aspirational:

    every accepted request is, at every instant, either COMPLETED or
    REDRIVABLE — a replica death (its records re-enter the pending
    queue) and even a router death (:meth:`RequestPlane.load` turns the
    journal's non-terminal records back into pending work) lose nothing.

The plane is transport-agnostic on purpose: records carry the one wire
schema (``serve.request.request_from_dict``) that the HTTP front end,
the stdin front end, and the router's dispatch all share, so the same
record can be accepted over HTTP, redriven over HTTP to a different
replica, and replayed offline through ``generate()`` for ``--verify``.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from torchpruner_tpu import obs
from torchpruner_tpu.obs import reqtrace
from torchpruner_tpu.resilience.manifest import (
    atomic_write_json,
    read_json,
)

JOURNAL_VERSION = 1

# record lifecycle states
ACCEPTED = "accepted"        # journaled, waiting for dispatch
DISPATCHED = "dispatched"    # a dispatch attempt is in flight
COMPLETED = "completed"      # tokens returned by some replica
FAILED = "failed"            # attempts/deadline exhausted (terminal)

_TERMINAL = (COMPLETED, FAILED)


@dataclass
class PlaneRecord:
    """One accepted request's durable state.  ``payload`` is the wire
    dict (``request_from_dict`` schema); ``deadline_epoch_s`` is
    wall-clock absolute so it survives a router restart."""

    rid: str
    payload: dict
    deadline_epoch_s: float
    accepted_epoch_s: float
    state: str = ACCEPTED
    #: distributed trace id minted at acceptance (obs.reqtrace) —
    #: journaled so a redriven/reloaded record keeps ONE waterfall
    trace_id: Optional[str] = None
    #: replica name of the CURRENT/latest dispatch attempt
    replica: Optional[str] = None
    attempts: int = 0
    #: times this record was re-queued off a failed/dead replica
    redrives: int = 0
    tokens: Optional[List[int]] = None
    completed_by: Optional[str] = None
    error: str = ""
    #: completion signal for front ends blocking on the result (never
    #: journaled)
    _event: threading.Event = field(default_factory=threading.Event,
                                    repr=False, compare=False)

    def remaining_s(self, now: Optional[float] = None) -> float:
        return max(0.0, self.deadline_epoch_s
                   - (time.time() if now is None else now))

    def terminal(self) -> bool:
        return self.state in _TERMINAL

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def to_json(self) -> dict:
        return {
            "rid": self.rid,
            "payload": self.payload,
            "deadline_epoch_s": self.deadline_epoch_s,
            "accepted_epoch_s": self.accepted_epoch_s,
            "state": self.state,
            "trace_id": self.trace_id,
            "replica": self.replica,
            "attempts": self.attempts,
            "redrives": self.redrives,
            "tokens": self.tokens,
            "completed_by": self.completed_by,
            "error": self.error,
        }

    @classmethod
    def from_json(cls, d: dict) -> "PlaneRecord":
        return cls(**{k: d.get(k) for k in (
            "rid", "payload", "deadline_epoch_s", "accepted_epoch_s",
            "state", "trace_id", "replica", "attempts", "redrives",
            "tokens", "completed_by", "error")})


class RequestPlane:
    """Thread-safe record store + FIFO pending queue + atomic journal.

    Every mutation happens under one lock and (when a ``journal_path``
    is set) lands on disk via ``atomic_write_json`` before the mutating
    call returns — :meth:`accept` in particular, so an acknowledged
    acceptance is durable by construction.  Completion is IDEMPOTENT:
    a hedged duplicate dispatch that finishes second is dropped (and
    counted), never double-recorded.
    """

    def __init__(self, journal_path: Optional[str] = None,
                 retain_terminal: int = 0):
        """``retain_terminal > 0`` (the long-running HTTP endpoint)
        compacts the journal: only the newest N TERMINAL records are
        retained, so per-transition flush cost stays bounded instead of
        growing with lifetime traffic.  0 (drills/batch) keeps
        everything — the drill's verify pass replays the full set."""
        self.journal_path = journal_path
        self.retain_terminal = int(retain_terminal)
        self._lock = threading.RLock()
        self._records: Dict[str, PlaneRecord] = {}
        self._pending: List[str] = []  # FIFO of rids awaiting dispatch
        self._ids = itertools.count()
        self.shed_total = 0
        self.duplicate_results_total = 0
        self.compacted_total = 0

    # -- construction / recovery -------------------------------------------

    @classmethod
    def load(cls, journal_path: str,
             retain_terminal: int = 0) -> "RequestPlane":
        """Rebuild a plane from a (possibly dead) router's journal.
        Non-terminal records — accepted AND dispatched, since a
        dispatched record whose router died has no worker anymore — go
        back to pending in acceptance order: the redrive-after-router-
        death path."""
        plane = cls(journal_path, retain_terminal=retain_terminal)
        raw = read_json(journal_path)
        max_id = -1
        # the lock makes the rebuild safe even if the caller hands the
        # plane to accepting threads before load() returns (and keeps
        # these writes honest under the shared-state lint)
        with plane._lock:
            for d in raw.get("records", []):
                rec = PlaneRecord.from_json(d)
                plane._records[rec.rid] = rec
                if rec.rid.startswith("r"):
                    try:
                        max_id = max(max_id, int(rec.rid[1:]))
                    except ValueError:
                        pass
                if rec.terminal():
                    rec._event.set()
                else:
                    if rec.state == DISPATCHED:
                        rec.redrives += 1
                    rec.state = ACCEPTED
                    rec.replica = None
                    plane._pending.append(rec.rid)
            plane._pending.sort(
                key=lambda rid: plane._records[rid].accepted_epoch_s)
            plane._ids = itertools.count(max_id + 1)
            plane.shed_total = int(raw.get("shed_total", 0))
        return plane

    def _compact_locked(self) -> None:
        """Evict the oldest terminal records past ``retain_terminal``
        (waiters keep their own record reference; only the plane's —
        and therefore the journal's — copy is dropped)."""
        if not self.retain_terminal:
            return
        terminal = [r for r in self._records.values() if r.terminal()]
        excess = len(terminal) - self.retain_terminal
        if excess <= 0:
            return
        terminal.sort(key=lambda r: r.accepted_epoch_s)
        for r in terminal[:excess]:
            del self._records[r.rid]
        self.compacted_total += excess

    def _flush_locked(self) -> None:
        if not self.journal_path:
            return
        atomic_write_json(self.journal_path, {
            "version": JOURNAL_VERSION,
            "written_epoch_s": time.time(),
            "shed_total": self.shed_total,
            "records": [r.to_json() for r in self._records.values()],
        })

    # -- admission ----------------------------------------------------------

    def accept(self, payload: dict, deadline_s: float) -> PlaneRecord:
        """Journal a new record (durable BEFORE return) and queue it."""
        with self._lock:
            rec = PlaneRecord(
                rid=f"r{next(self._ids):05d}", payload=dict(payload),
                deadline_epoch_s=time.time() + float(deadline_s),
                accepted_epoch_s=time.time())
            rec.trace_id = reqtrace.mint_trace_id(rec.rid)
            self._records[rec.rid] = rec
            self._pending.append(rec.rid)
            t0 = time.perf_counter()
            self._flush_locked()
            flush_s = time.perf_counter() - t0
        obs.inc("fleet_accepted_total",
                help="requests accepted into the fleet request plane "
                     "(journaled: completed or redrivable from here on)")
        # the first two trace stages: the acceptance anchor and the
        # durability cost paid before the ack
        reqtrace.stage(rec.trace_id, "accept", rid=rec.rid,
                       t_start=rec.accepted_epoch_s)
        reqtrace.stage(rec.trace_id, "journal_flush", dur_s=flush_s,
                       rid=rec.rid)
        return rec

    def note_shed(self) -> None:
        """Count an admission-time shed (no record: a shed request was
        never accepted, so it is outside the zero-loss set — the caller
        got its 429/503 + Retry-After instead)."""
        with self._lock:
            self.shed_total += 1
            self._flush_locked()

    # -- dispatch lifecycle --------------------------------------------------

    def checkout(self) -> Optional[PlaneRecord]:
        """Pop the oldest pending record and mark it dispatched."""
        with self._lock:
            if not self._pending:
                return None
            rec = self._records[self._pending.pop(0)]
            rec.state = DISPATCHED
            self._flush_locked()
            return rec

    def checkout_expired(self) -> Optional[PlaneRecord]:
        """Pop the oldest pending record whose deadline already expired
        — the capacity-gated router's escape hatch: even with zero
        dispatch capacity, a record must still FAIL loudly at its
        deadline rather than age silently in the queue."""
        with self._lock:
            for i, rid in enumerate(self._pending):
                rec = self._records[rid]
                if rec.remaining_s() <= 0.0:
                    self._pending.pop(i)
                    rec.state = DISPATCHED
                    self._flush_locked()
                    return rec
            return None

    def assign(self, rid: str, replica: str) -> None:
        """Record which replica the current attempt targets (the
        redrive map's key)."""
        with self._lock:
            rec = self._records.get(rid)
            if rec is None or rec.terminal():
                return
            rec.replica = replica
            rec.attempts += 1
            self._flush_locked()

    def release(self, rid: str, *, redrive: bool = False) -> bool:
        """Back to pending (front of the FIFO — a redriven record is
        the oldest work in the plane).  No-op on terminal records."""
        with self._lock:
            rec = self._records.get(rid)
            if rec is None or rec.terminal() or rid in self._pending:
                return False
            rec.state = ACCEPTED
            rec.replica = None
            if redrive:
                rec.redrives += 1
            self._pending.insert(0, rid)
            self._flush_locked()
        if redrive:
            obs.inc("fleet_redrive_total",
                    help="journaled requests re-queued off a dead/"
                         "failed replica to a survivor")
            reqtrace.stage(rec.trace_id, "redrive", rid=rid,
                           redrives=rec.redrives)
        return True

    def complete(self, rid: str, tokens: List[int],
                 replica: str) -> bool:
        """Idempotent terminal transition; ``False`` drops a hedged
        duplicate (first completion wins)."""
        with self._lock:
            rec = self._records.get(rid)
            if rec is None:
                return False
            if rec.terminal():
                self.duplicate_results_total += 1
                obs.inc("fleet_duplicate_results_total",
                        help="hedged dispatches finishing after their "
                             "record was already terminal (dropped)")
                return False
            rec.state = COMPLETED
            rec.tokens = list(tokens)
            rec.completed_by = replica
            rec.error = ""
            self._compact_locked()
            self._flush_locked()
            rec._event.set()
        obs.inc("fleet_completed_total",
                help="fleet requests completed by some replica")
        e2e = max(0.0, time.time() - rec.accepted_epoch_s)
        obs.observe("reqtrace_e2e_seconds", e2e,
                    help="fleet request acceptance -> completion "
                         "(router-observed end-to-end latency)")
        reqtrace.finish(rec.trace_id, outcome="complete",
                        e2e_s=round(e2e, 6), rid=rid, replica=replica,
                        attempts=rec.attempts, redrives=rec.redrives)
        return True

    def fail(self, rid: str, error: str) -> bool:
        """Terminal failure (deadline/attempts exhausted) — counted
        loudly: a failed ACCEPTED request is exactly the loss the
        failover drill asserts to be zero."""
        with self._lock:
            rec = self._records.get(rid)
            if rec is None or rec.terminal():
                return False
            rec.state = FAILED
            rec.error = str(error)[:500]
            self._compact_locked()
            self._flush_locked()
            rec._event.set()
        obs.inc("fleet_failed_total",
                help="accepted requests that exhausted their retry/"
                     "deadline budget (accepted-request LOSS)")
        reqtrace.finish(rec.trace_id, outcome="failed", rid=rid,
                        error=rec.error)
        return True

    # -- views --------------------------------------------------------------

    @property
    def pending_depth(self) -> int:
        with self._lock:
            return len(self._pending)

    def oldest_pending_age_s(self, now: Optional[float] = None) -> float:
        """Age (seconds) of the OLDEST record still awaiting dispatch —
        the ``fleet_queue_age_seconds`` gauge and the autoscaling
        supervisor's primary scale-up signal: depth alone can look
        small while one starved request ages past its deadline.
        Redriven records re-enter at the FRONT of the FIFO, so their
        original acceptance time keeps counting (a redrive must not
        reset the starvation clock).  0.0 when nothing is pending."""
        with self._lock:
            if not self._pending:
                return 0.0
            oldest = min(self._records[rid].accepted_epoch_s
                         for rid in self._pending)
        return max(0.0, (time.time() if now is None else now) - oldest)

    def get(self, rid: str) -> Optional[PlaneRecord]:
        with self._lock:
            return self._records.get(rid)

    def records(self) -> List[PlaneRecord]:
        with self._lock:
            return list(self._records.values())

    def assigned_to(self, replica: str) -> List[str]:
        """Rids whose current dispatch targets ``replica`` — the set a
        death hedge re-dispatches."""
        with self._lock:
            return [r.rid for r in self._records.values()
                    if r.state == DISPATCHED and r.replica == replica]

    def counts(self) -> Dict[str, int]:
        with self._lock:
            out = {s: 0 for s in (ACCEPTED, DISPATCHED, COMPLETED,
                                  FAILED)}
            for r in self._records.values():
                out[r.state] = out.get(r.state, 0) + 1
            out["pending"] = len(self._pending)
            out["shed"] = self.shed_total
            return out

    def all_terminal(self) -> bool:
        with self._lock:
            return all(r.terminal() for r in self._records.values())

"""SLO-driven autoscaling supervisor with a graceful-degradation ladder.

Closes the scale loop over the fleet: rolling queue-age / SLO signals
in, ledgered membership changes out.  Design contracts (each one a
robustness property the chaos drill asserts):

- **Decision BEFORE effect** — every decision is journaled to the run
  ledger (``obs.record_serve(kind="scale_decision", ...)``) with its
  triggering signals *before* any process is spawned or retired (the
  search driver's decide-then-act discipline): a crash mid-action
  leaves a ledger that explains the intent.
- **Predict before launch** — the PR 10/11 cost model's
  ``predict_decode`` twin estimates per-replica capacity (step ms →
  tok/s at the serving geometry) and the estimate rides every scale-up
  record, so the ledger answers "what did we think one more replica
  would buy?" — capacity planning with a paper trail.
- **Drain-then-remove** — scale-down marks the victim ``retiring``
  (``FleetRouter.begin_retire``: no new dispatches), waits until the
  router holds no in-flight work for it and no plane record is
  assigned to it, THEN SIGTERM-drains the process and drops the view.
  An accepted request can therefore never be lost to a scale-down.
- **Hysteresis** — scale signals must persist for ``up_ticks`` /
  ``down_ticks`` consecutive evaluations and respect a post-action
  cooldown, so a noisy p99 cannot flap the fleet (pinned by a unit
  test driving the evaluator with alternating signals).
- **Degradation ladder** — when the fleet is at ``max_replicas`` and
  still drowning, capacity is bought back in ledgered, reversible
  rungs: (1) shed the batch tier at admission
  (``router.shed_tenants``), (2) tighten admission
  (``router.force_degraded`` → the existing degraded-mode queue
  factor), (3) optionally rolling-swap replicas to a PRUNED checkpoint
  (PR 6 hot-swap) — the lever only this repo has: the pruner
  manufactures the cheaper model the ladder degrades to.  Recovery
  steps back down the same rungs in reverse order.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from torchpruner_tpu import obs
from torchpruner_tpu.fleet.router import FleetRouter

#: ladder rungs in escalation order (index == severity)
RUNGS = ("none", "shed_batch", "tighten_admission", "pruned_swap")


@dataclass(frozen=True)
class ScalePolicy:
    """The supervisor's knobs.  Defaults are drill-scaled (seconds);
    production would stretch the windows, not the structure."""

    min_replicas: int = 1
    max_replicas: int = 4
    #: scale up when the oldest pending record is older than this
    queue_age_up_s: float = 1.5
    #: eligible to scale down only when queue age is below this
    queue_age_down_s: float = 0.25
    #: ... and when at least this fraction of live replicas sit in an
    #: SLO-breach episode (either signal scales up)
    breach_frac_up: float = 0.5
    #: consecutive signalled evaluations before acting (hysteresis)
    up_ticks: int = 3
    down_ticks: int = 12
    #: post-action quiet period (also hysteresis: an action's effect
    #: needs time to show up in the signals it changes)
    cooldown_s: float = 3.0
    #: extra consecutive up-signals while already at max_replicas
    #: before climbing a degradation rung
    degrade_ticks: int = 3
    #: drain-then-remove budget; an overrunning drain is cancelled
    #: (victim returns to service) and ledgered as scale_error
    drain_timeout_s: float = 120.0
    #: tenants sheddable at rung 1 (the batch tier)
    shed_tenants: tuple = ()
    #: rung 3: pruned checkpoint to rolling-swap toward (None skips
    #: the rung), and the checkpoint to swap back on recovery
    pruned_checkpoint: Optional[str] = None
    restore_checkpoint: Optional[str] = None


@dataclass
class ScaleEvent:
    """One applied decision (the drill summary's scale log)."""

    t_s: float
    action: str
    trigger: dict
    detail: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return {"t_s": round(self.t_s, 3), "action": self.action,
                "trigger": self.trigger, **self.detail}


class Supervisor:
    """See module docstring.  ``launcher`` abstracts process control:

    - ``launcher.launch() -> ReplicaClient`` — spawn one replica,
      block until it listens, return its client (runs on a background
      thread; the traffic loop never stalls on a model load).
    - ``launcher.retire(name) -> None`` — SIGTERM-drain and reap the
      named replica's process (called only after the router-side drain
      gate passed).

    ``capacity`` is the cost-model prediction dict attached to every
    scale-up record (``predicted_step_ms`` / ``predicted_tok_s`` ...);
    pass :func:`predict_replica_capacity`'s result.  ``now`` injects a
    clock for the hysteresis unit tests."""

    def __init__(self, router: FleetRouter, policy: ScalePolicy, *,
                 launcher=None, capacity: Optional[dict] = None,
                 now: Optional[Callable[[], float]] = None):
        self.router = router
        self.policy = policy
        self.launcher = launcher
        self.capacity = capacity
        self._now = now or time.monotonic
        self._t0 = self._now()
        self._up = 0
        self._down = 0
        self._at_max = 0
        self._last_action_t = -1e9
        self.rung = 0
        self.events: List[ScaleEvent] = []
        self._op: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self.errors: List[str] = []

    # -- signals -------------------------------------------------------------

    def signals(self) -> dict:
        """One evaluation's sensor readings (all router-side: queue
        age from the plane, breach fraction and membership from the
        health views)."""
        r = self.router
        with r._lock:
            views = list(r.views.values())
            live = [v for v in views if v.live]
            breached = [v for v in live if v.state == "slo_breach"]
            retiring = sum(1 for v in views if v.retiring)
        return {
            "queue_age_s": round(r.plane.oldest_pending_age_s(), 3),
            "pending": r.plane.pending_depth,
            "replicas": len(views),
            "live": len(live),
            "breach_frac": (len(breached) / len(live)) if live else 0.0,
            "retiring": retiring,
            "rung": RUNGS[self.rung],
        }

    # -- the decision core (pure w.r.t. the router; unit-testable) ----------

    def evaluate(self, sig: dict,
                 now: Optional[float] = None) -> Optional[str]:
        """Fold one signal sample into the hysteresis counters and
        return the action to take, if any: ``scale_up`` /
        ``scale_down`` / ``degrade`` / ``recover``.  Consecutive-tick
        counters + cooldown mean a flapping signal yields NO action —
        the no-flap property the tests pin."""
        p = self.policy
        now = self._now() if now is None else now
        up = (sig["queue_age_s"] >= p.queue_age_up_s
              or sig["breach_frac"] >= p.breach_frac_up)
        down = (sig["queue_age_s"] <= p.queue_age_down_s
                and sig["pending"] == 0 and not up)
        self._up = self._up + 1 if up else 0
        self._down = self._down + 1 if down else 0
        at_max = sig["replicas"] - sig["retiring"] >= p.max_replicas
        self._at_max = self._at_max + 1 if (up and at_max) else 0
        if now - self._last_action_t < p.cooldown_s:
            return None
        if self._up >= p.up_ticks:
            if not at_max:
                return "scale_up"
            if self._at_max >= p.degrade_ticks \
                    and self.rung < len(RUNGS) - 1:
                return "degrade"
            return None
        if self._down >= p.down_ticks:
            if self.rung > 0:
                return "recover"
            if sig["replicas"] - sig["retiring"] > p.min_replicas:
                return "scale_down"
        return None

    # -- actuation -----------------------------------------------------------

    def _ledger(self, action: str, sig: dict, **detail) -> None:
        """Journal the decision (ledger + counters) BEFORE its effect.
        Every record carries ``correlation_id`` — the incident/anomaly
        in effect when the decision was taken (or null) — so a
        postmortem links decision→signal without timestamp guessing,
        and the incident correlator ranks the decision as a suspect
        with the link already in the evidence (obs.incident)."""
        rec = {"action": action, "trigger": sig,
               "correlation_id": obs.active_incident_id(), **detail}
        obs.record_serve(kind="scale_decision", t_s=round(
            self._now() - self._t0, 3), **rec)
        obs.inc(f"scale_{action}_total",
                help=f"supervisor {action} decisions (ledgered before "
                     f"effect)")
        obs.inc("scale_decisions_total",
                help="supervisor scale/degrade decisions of any kind")
        self.events.append(ScaleEvent(
            t_s=self._now() - self._t0, action=action, trigger=sig,
            detail=detail))

    def _busy(self) -> bool:
        with self._lock:
            return self._op is not None and self._op.is_alive()

    def _start_op(self, target, name: str) -> None:
        with self._lock:
            self._op = threading.Thread(target=target, name=name,
                                        daemon=True)
            self._op.start()

    def tick(self) -> None:
        """One supervision step: read signals, maybe act.  Actions run
        on a background thread (model loads take seconds; the traffic
        loop must not stall), one at a time — which is itself a flap
        guard: no second decision while the first is still landing."""
        sig = self.signals()
        obs.gauge_set("scale_replicas", sig["replicas"],
                      help="replicas in the routing set (supervisor "
                           "view)")
        obs.gauge_set("scale_rung", self.rung,
                      help="degradation-ladder rung (0 = none)")
        if self._busy():
            return
        action = self.evaluate(sig)
        if action is None:
            return
        self._last_action_t = self._now()
        self._up = self._down = self._at_max = 0
        if action == "scale_up":
            self._scale_up(sig)
        elif action == "scale_down":
            self._scale_down(sig)
        elif action == "degrade":
            self._climb(sig)
        elif action == "recover":
            self._descend(sig)

    # each rung / scale verb: ledger first, then act

    def _scale_up(self, sig: dict) -> None:
        if self.launcher is None:
            self.errors.append("scale_up: no launcher")
            return
        self._ledger("scale_up", sig, capacity=self.capacity)

        def op():
            try:
                client = self.launcher.launch()
                self.router.add_replica(client)
                self.router.check_health(force=True)
            except Exception as e:  # noqa: BLE001 - supervisor must survive
                self.errors.append(f"scale_up: {type(e).__name__}: {e}")
                obs.inc("scale_errors_total",
                        help="supervisor actions that failed to land")
        self._start_op(op, "supervisor-scale-up")

    def _pick_victim(self) -> Optional[str]:
        """Newest non-retiring replica (LIFO: the scale-up surge
        capacity leaves first, the seed replicas keep their warm
        prefix caches)."""
        with self.router._lock:
            names = [n for n, v in self.router.views.items()
                     if not v.retiring]
        return names[-1] if len(names) > self.policy.min_replicas \
            else None

    def _scale_down(self, sig: dict) -> None:
        victim = self._pick_victim()
        if victim is None or self.launcher is None:
            return
        self._ledger("scale_down", sig, replica=victim)
        self.router.begin_retire(victim)

        def op():
            deadline = self._now() + self.policy.drain_timeout_s
            while self._now() < deadline:
                if self.router.retired_idle(victim):
                    try:
                        self.launcher.retire(victim)
                    except Exception as e:  # noqa: BLE001
                        self.errors.append(
                            f"retire {victim}: {type(e).__name__}: {e}")
                    self.router.remove_replica(victim)
                    return
                time.sleep(0.05)
            # overran the drain budget: put the victim back in service
            # (losing the scale-down beats losing a request)
            self.router.cancel_retire(victim)
            self.errors.append(f"scale_down: drain of {victim} "
                               f"overran {self.policy.drain_timeout_s}s")
            obs.inc("scale_errors_total",
                    help="supervisor actions that failed to land")
        self._start_op(op, "supervisor-scale-down")

    def _climb(self, sig: dict) -> None:
        rung = self.rung + 1
        if rung == 3 and self.policy.pruned_checkpoint is None:
            return  # optional rung not configured
        self._ledger("degrade", sig, rung=RUNGS[rung])
        self.rung = rung
        if rung == 1:
            with self.router._lock:
                self.router.shed_tenants |= set(
                    self.policy.shed_tenants)
        elif rung == 2:
            with self.router._lock:
                self.router.force_degraded = True
        elif rung == 3:
            ckpt = self.policy.pruned_checkpoint

            def op():
                try:
                    self.router.rolling_swap(ckpt)
                except Exception as e:  # noqa: BLE001
                    self.errors.append(
                        f"pruned_swap: {type(e).__name__}: {e}")
                    obs.inc("scale_errors_total",
                            help="supervisor actions that failed to "
                                 "land")
            self._start_op(op, "supervisor-pruned-swap")

    def _descend(self, sig: dict) -> None:
        rung = self.rung
        self._ledger("recover", sig, rung=RUNGS[rung])
        if rung == 1:
            with self.router._lock:
                self.router.shed_tenants -= set(
                    self.policy.shed_tenants)
        elif rung == 2:
            with self.router._lock:
                self.router.force_degraded = False
        elif rung == 3 and self.policy.restore_checkpoint:
            ckpt = self.policy.restore_checkpoint

            def op():
                try:
                    self.router.rolling_swap(ckpt)
                except Exception as e:  # noqa: BLE001
                    self.errors.append(
                        f"restore_swap: {type(e).__name__}: {e}")
            self._start_op(op, "supervisor-restore-swap")
        self.rung = rung - 1

    # -- teardown / reporting ------------------------------------------------

    def join(self, timeout_s: float = 120.0) -> None:
        """Wait for any in-flight scale operation to land."""
        with self._lock:
            op = self._op
        if op is not None:
            op.join(timeout_s)

    def summary(self) -> dict:
        return {
            "events": [e.to_json() for e in self.events],
            "scale_ups": sum(e.action == "scale_up"
                             for e in self.events),
            "scale_downs": sum(e.action == "scale_down"
                               for e in self.events),
            "degrades": sum(e.action == "degrade" for e in self.events),
            "recovers": sum(e.action == "recover" for e in self.events),
            "rung": RUNGS[self.rung],
            "errors": list(self.errors),
        }


def predict_replica_capacity(model, *, n_slots: int, max_len: int,
                             cache_dtype=None) -> Optional[dict]:
    """Cost-model capacity estimate for ONE replica at the serving
    geometry — computed BEFORE any launch, attached to every scale-up
    ledger record.  tok/s upper bound = all slots decode every step =
    ``n_slots / step_s``.  Best-effort like every cost-model surface
    (None on unsupported models / disabled prediction)."""
    from torchpruner_tpu.analysis.cost_model import predict_decode

    pred = predict_decode(model, n_slots=n_slots, max_len=max_len,
                          cache_dtype=cache_dtype)
    if pred is None:
        return None
    step_ms = pred.step_ms
    return {
        "device_kind": pred.device_kind,
        "predicted_step_ms": round(step_ms, 4),
        "predicted_tok_s": round(n_slots / max(1e-9, step_ms / 1e3), 1),
        "n_slots": int(n_slots),
        "max_len": int(max_len),
        "bound": pred.bound,
    }

"""``python -m torchpruner_tpu fleet`` — the multi-replica serving plane.

Spawns N single-replica serve processes (``serve --http``, each with its
own obs dir, bounded queue, and drain snapshot dir), fronts them with
the health-checked :class:`~torchpruner_tpu.fleet.router.FleetRouter`
over a durable :class:`~torchpruner_tpu.fleet.plane.RequestPlane`
journal, and runs one of two modes:

- ``--synthetic N`` — the FAILOVER DRILL: N seeded synthetic requests
  on an open-loop Poisson schedule (``--rate`` req/s), optional fleet
  chaos (``--chaos '{"kill_replica_at_step": 8}'`` SIGKILLs a replica
  once the router has dispatched 8 requests; ``hang_replica_at_step``
  SIGSTOPs it; ``slow_replica_ms`` degrades one replica's per-step
  latency via the core chaos env), optional ``--swap-checkpoint`` (a
  rolling fleet upgrade mid-drill), then: SIGTERM-drains the
  survivors, merges every replica's obs shard into ONE fleet-wide
  report, ``--verify`` re-decodes every completed request from the
  JOURNAL through solo ``generate()`` (bit-identity: the redrive
  correctness contract), prints a JSON summary, and exits non-zero on
  ANY accepted-request loss or verify mismatch.
- ``--http PORT`` — the serving-plane endpoint: ``POST /v1/generate``
  accepts into the journal (durable before the 200 path starts) and
  blocks for the routed result; over-capacity answers 429/503 +
  Retry-After by the router's (degradation-tightened) admission
  policy; ``GET /healthz`` / ``GET /stats`` expose the fleet view.

Every replica is started with the SAME seed/checkpoint and geometry, so
a redriven request re-decodes bit-identically on any survivor — greedy
requests always, sampled requests because their rng is seed-pinned (see
the README caveat: that guarantee is a property of identical replicas,
not of redrive itself).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import threading
import time
from dataclasses import dataclass
from typing import List, Optional

from torchpruner_tpu.fleet.plane import COMPLETED, RequestPlane
from torchpruner_tpu.fleet.replica import ReplicaProcess, free_port
from torchpruner_tpu.serve.request import request_from_dict
from torchpruner_tpu.fleet.report import (
    merge_replica_shards,
    merge_timeseries,
)
from torchpruner_tpu.fleet.router import FleetRouter, RouterPolicy

JOURNAL_FILENAME = "fleet_journal.json"


@dataclass
class FleetChaos:
    """Driver-side fleet fault injection (the chaos harness's fleet
    extension): ``*_at_step`` counts ROUTER DISPATCHES (deterministic
    under a fixed arrival schedule), ``replica_index`` picks the
    victim.  ``slow_replica_ms`` is forwarded to the victim's env as
    core chaos ``slow_steps_ms`` (a per-decode-step stall)."""

    kill_replica_at_step: int = -1
    hang_replica_at_step: int = -1
    slow_replica_ms: float = 0.0
    replica_index: int = 0

    @classmethod
    def from_any(cls, spec) -> "FleetChaos":
        if spec is None:
            return cls()
        if isinstance(spec, cls):
            return spec
        if isinstance(spec, str):
            spec = json.loads(spec)
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(spec) - known
        if unknown:
            raise ValueError(f"unknown fleet chaos keys: "
                             f"{sorted(unknown)} (known: {sorted(known)})")
        return cls(**spec)


def replica_argv(preset: str, port: int, args,
                 obs_dir: str, run_dir: str) -> List[str]:
    """The serve subcommand line one replica runs."""
    argv = [sys.executable, "-m", "torchpruner_tpu", "serve", preset,
            "--http", str(port), "--slots", str(args.slots),
            "--max-len", str(args.max_len), "--seed", str(args.seed),
            "--queue-bound", str(args.replica_queue_bound),
            "--obs-dir", obs_dir, "--run-dir", run_dir,
            "--trace-sample-every", str(args.trace_sample_every),
            "--timeout", str(args.deadline_s)]
    if args.page_len > 0:
        argv += ["--page-len", str(args.page_len)]
    if args.prefix_pages > 0:
        # Serve v2 on every replica: same pool/chunk geometry fleet-
        # wide, so any replica serves any session (affinity is a
        # throughput hint, failover stays free)
        argv += ["--prefix-pages", str(args.prefix_pages),
                 "--prefill-chunk", str(args.prefill_chunk),
                 "--prefill-cap", str(args.prefill_cap)]
    if args.smoke:
        argv.append("--smoke")
    if args.cpu:
        argv.append("--cpu")
    if args.checkpoint:
        argv += ["--checkpoint", args.checkpoint]
    if args.slo_ttft_p99_ms is not None:
        argv += ["--slo-ttft-p99-ms", str(args.slo_ttft_p99_ms)]
    if args.slo_token_p99_ms is not None:
        argv += ["--slo-token-p99-ms", str(args.slo_token_p99_ms)]
    if args.slo_queue_p99_ms is not None:
        argv += ["--slo-queue-p99-ms", str(args.slo_queue_p99_ms)]
    if getattr(args, "tenants_json", None):
        # the scenario's QoS table rides to every replica: the tenant
        # policies are committed WITH the traffic (one contract)
        argv += ["--tenants", args.tenants_json]
    return argv


def spawn_fleet(preset: str, args, fleet_dir: str,
                chaos: FleetChaos) -> List[ReplicaProcess]:
    """Spawn + wait-listening on every replica.  All replicas share the
    seed/checkpoint and geometry — the redrive bit-identity contract."""
    from torchpruner_tpu import obs

    procs: List[ReplicaProcess] = []
    for i in range(args.replicas):
        port = free_port()
        obs_dir = os.path.join(fleet_dir, "obs", f"replica{i}")
        run_dir = os.path.join(fleet_dir, f"replica{i}_run")
        env = dict(os.environ)
        env.pop("TORCHPRUNER_CHAOS", None)  # fleet chaos is driver-side
        if chaos.slow_replica_ms > 0 and i == chaos.replica_index:
            env["TORCHPRUNER_CHAOS"] = json.dumps(
                {"slow_steps_ms": chaos.slow_replica_ms})
            # the planted fault is provenance: ledgered at injection
            # time, so the incident correlator can NAME it — the CI
            # planted-cause drill asserts the top-ranked suspect is
            # this record, by replica and event class (obs.incident)
            obs.record_serve(kind="chaos_injection",
                             chaos="slow_replica",
                             replica=f"replica{i}",
                             slow_steps_ms=chaos.slow_replica_ms)
        rep = ReplicaProcess(
            name=f"replica{i}", port=port,
            argv=replica_argv(preset, port, args, obs_dir, run_dir),
            env=env,
            log_path=os.path.join(fleet_dir, f"replica{i}.log"))
        rep.obs_dir = obs_dir
        rep.spawn()
        procs.append(rep)
    for rep in procs:
        if not rep.wait_listening(timeout_s=args.startup_timeout_s):
            for r in procs:
                r.kill9()
            raise SystemExit(
                f"fleet: {rep.name} never started listening "
                f"(see {rep.log_path})")
    return procs


def _payload_of(req) -> dict:
    """serve.Request → the wire dict (request_from_dict schema)."""
    s = req.sampling
    out = {"prompt_ids": req.prompt_ids.tolist(),
           "max_new": int(req.max_new), "eos_id": req.eos_id,
           "temperature": s.temperature, "top_k": s.top_k,
           "top_p": s.top_p, "seed": s.seed}
    if req.session_id:
        out["session_id"] = req.session_id
    if req.tenant:
        out["tenant"] = req.tenant
    return out


class _ChaosTrigger:
    """Fires the driver-side injections at their dispatch-count step."""

    def __init__(self, chaos: FleetChaos, procs: List[ReplicaProcess]):
        self.chaos, self.procs = chaos, procs
        self.killed: List[str] = []
        self.hung: List[str] = []

    def __call__(self, router: FleetRouter) -> None:
        from torchpruner_tpu import obs

        c = self.chaos
        idx = c.replica_index
        if 0 <= c.kill_replica_at_step <= router.dispatched_total \
                and not self.killed and idx < len(self.procs):
            victim = self.procs[idx]
            print(f"[fleet] chaos: kill -9 {victim.name} at dispatch "
                  f"{router.dispatched_total}", file=sys.stderr,
                  flush=True)
            obs.record_serve(kind="chaos_injection",
                             chaos="kill_replica", replica=victim.name,
                             at_dispatch=router.dispatched_total)
            victim.kill9()
            self.killed.append(victim.name)
        if 0 <= c.hang_replica_at_step <= router.dispatched_total \
                and not self.hung and idx < len(self.procs):
            victim = self.procs[idx]
            print(f"[fleet] chaos: SIGSTOP {victim.name} at dispatch "
                  f"{router.dispatched_total}", file=sys.stderr,
                  flush=True)
            obs.record_serve(kind="chaos_injection",
                             chaos="hang_replica", replica=victim.name,
                             at_dispatch=router.dispatched_total)
            victim.hang()
            self.hung.append(victim.name)


def _finalize_tracing(fleet_obs_dir: str) -> dict:
    """The drill/endpoint's trace epilogue, run while the fleet session
    is still open: flush pending exemplars, assemble every process's
    stage events into cross-process request traces, compute the
    TTFT/E2E latency budget over the MERGED stage histograms, and land
    budget + assembly verdict as gauges (``ttft_stage_*_pct`` /
    ``reqtrace_*``, gated by ``obs diff``) and a ledger ``reqtrace``
    record (rendered by ``obs report``).  Returns the summary fields
    the drill prints."""
    from torchpruner_tpu import obs
    from torchpruner_tpu.fleet import report as fleet_report
    from torchpruner_tpu.obs import aggregate, reqtrace

    session = obs.get()
    if session is None:
        return {}
    reqtrace.session_flush()
    # NOTE: the merged trace.json is assembled AGAIN after
    # obs.shutdown (fleet_main) — intentionally, not redundantly: the
    # router's stream gains this function's own flushes and the
    # session-close records, so the file must re-read it; this pass
    # only needs the traces + summary while the session can still
    # take gauges/ledger records
    traces = fleet_report.assemble_fleet_traces(fleet_obs_dir)
    tsum = fleet_report.trace_summary(traces)
    try:
        merged = aggregate.merged_registry(fleet_obs_dir,
                                           local=session.metrics)
        budget = reqtrace.latency_budget(merged.snapshot())
    except Exception:
        budget = None
    reqtrace.install_budget_gauges(budget)
    obs.gauge_set("reqtrace_traces_assembled", tsum["assembled"],
                  help="cross-process request traces assembled from "
                       "the fleet's event streams")
    obs.gauge_set("reqtrace_traces_cross_process", tsum["cross_process"],
                  help="completed traces whose waterfall spans router "
                       "AND replica pids (contiguity verdict)")
    obs.gauge_set("reqtrace_traces_torn", tsum["torn"],
                  help="traces with stage events but no terminal "
                       "summary from any process")
    exemplars = fleet_report.slowest_exemplars(traces)
    obs.record_reqtrace(budget=budget, assembly=tsum,
                        exemplars=exemplars)
    out = {
        "traces_assembled": tsum["assembled"],
        "traces_cross_process": tsum["cross_process"],
        "traces_redriven_cross_process": tsum["redriven_cross_process"],
        "traces_torn": tsum["torn"],
    }
    ttft = (budget or {}).get("ttft") or {}
    if ttft.get("recon_pct") is not None:
        out["ttft_recon_pct"] = round(ttft["recon_pct"], 2)
    stages = sorted((r for r in ttft.get("stages") or []
                     if r.get("pct") is not None),
                    key=lambda r: -r["pct"])
    if stages:
        out["ttft_budget_top2"] = [[r["stage"], round(r["pct"], 1)]
                                   for r in stages[:2]]
    return out


def run_drill(preset: str, args, fleet_dir: str,
              chaos: FleetChaos) -> int:
    """The synthetic failover drill (see module docstring)."""
    from torchpruner_tpu import obs
    from torchpruner_tpu.serve.engine import vocab_of
    from torchpruner_tpu.serve.frontend import _resolve_model
    from torchpruner_tpu.serve.traffic import (
        poisson_arrivals,
        shared_prefix_requests,
        synthetic_requests,
    )

    # the driver's own copy of the weights — vocab for the synthetic
    # prompts now, solo-decode replays for --verify later
    model, params, _meta = _resolve_model(
        preset, smoke=args.smoke, seed=args.seed,
        checkpoint=args.checkpoint)
    n = args.synthetic
    prompt_lens = [int(x) for x in args.prompt_lens.split(",") if x]
    max_new = [int(x) for x in args.max_new.split(",") if x]
    if args.shared_prefixes > 0:
        reqs = shared_prefix_requests(
            n, vocab=vocab_of(model), n_prefixes=args.shared_prefixes,
            prefix_len=args.prefix_len, suffix_lens=prompt_lens,
            max_new=max_new, seed=args.seed, sessions=args.sessions,
            temperature=args.temperature)
    else:
        reqs = synthetic_requests(
            n, vocab=vocab_of(model), prompt_lens=prompt_lens,
            max_new=max_new, seed=args.seed,
            temperature=args.temperature)
    payloads = [_payload_of(r) for r in reqs]
    arrivals = poisson_arrivals(n, args.rate, seed=args.seed)

    procs = spawn_fleet(preset, args, fleet_dir, chaos)
    plane = RequestPlane(os.path.join(fleet_dir, JOURNAL_FILENAME))
    router = FleetRouter(plane, procs, policy=_policy_of(args))
    trigger = _ChaosTrigger(chaos, procs)
    swap_thread = None
    t0 = time.monotonic()
    try:
        router.check_health(force=True)
        i = 0
        shed = 0
        while True:
            now = time.monotonic() - t0
            while i < n and arrivals[i] <= now:
                if router.submit(payloads[i],
                                 deadline_s=args.deadline_s) is None:
                    shed += 1
                i += 1
            router.tick()
            trigger(router)
            if swap_thread is None and args.swap_checkpoint \
                    and router.dispatched_total >= args.swap_after:
                swap_thread = threading.Thread(
                    target=router.rolling_swap,
                    args=(args.swap_checkpoint,), daemon=True)
                swap_thread.start()
            if i >= n and plane.all_terminal() \
                    and plane.pending_depth == 0:
                break
            if now > args.drill_timeout_s:
                print(f"[fleet] drill timed out: {plane.counts()}",
                      file=sys.stderr, flush=True)
                break
            time.sleep(0.01)
        if swap_thread is not None:
            swap_thread.join(timeout=args.drill_timeout_s)
    finally:
        router.close()
        exit_codes = {p.name: p.drain(timeout_s=args.startup_timeout_s)
                      for p in procs}
    wall = time.monotonic() - t0

    # fleet-wide report: every survivor's obs shard merged into the
    # fleet session's registry (BEFORE obs.shutdown exports it)
    shards = merge_replica_shards(
        os.path.join(fleet_dir, "obs"), [p.obs_dir for p in procs])
    # fleet-wide time-series: every process's windows re-homed onto the
    # router clock (metrics_ts_fleet.jsonl; re-merged after obs.shutdown
    # in fleet_main so the router's final window lands too)
    try:
        ts_merge = merge_timeseries(
            os.path.join(fleet_dir, "obs"), [p.obs_dir for p in procs])
    except Exception:
        ts_merge = {"streams": 0, "windows": 0}
    # tracing BEFORE burn collection: the ledgered reqtrace record
    # (slowest-K exemplars) must exist when a re-recorded burn alert
    # triggers the incident correlator, so the incident carries them
    trace_fields = _finalize_tracing(os.path.join(fleet_dir, "obs"))
    # replica-ledgered burn-rate alerts re-homed into the FLEET ledger
    # (each re-record fires the obs.record_serve incident hook — this
    # is where fleet incidents assemble), and the drill's pass/fail
    # signal: the planted slow_replica_ms drill must fire one
    burn_alerts = _collect_burn_alerts(procs)

    records = plane.records()
    completed = [r for r in records if r.state == COMPLETED]
    lost = [r for r in records if r.state != COMPLETED]
    redrives = sum(r.redrives for r in records)
    mismatches = 0
    if args.verify:
        mismatches = _verify_from_journal(model, params, completed,
                                          max_len=args.max_len)
    summary = {
        "mode": "drill",
        "replicas": args.replicas,
        "requests": n,
        "accepted": len(records),
        "completed": len(completed),
        "lost": len(lost),
        "shed": shed,
        "redrives": redrives,
        "failovers": router.failovers_total,
        "duplicates": plane.duplicate_results_total,
        "killed": trigger.killed,
        "hung": trigger.hung,
        "replica_exit_codes": exit_codes,
        "shards_merged": sum(bool(v) for v in shards.values()),
        "ts_streams": ts_merge["streams"],
        "ts_windows": ts_merge["windows"],
        "slo_burn_alerts": len(burn_alerts),
        **_incident_counts(),
        "affinity_preferred": router.affinity_preferred_total,
        "affinity_hits": router.affinity_hits_total,
        "affinity_hit_rate": round(
            router.affinity_hits_total
            / max(1, router.affinity_preferred_total), 4),
        "wall_s": round(wall, 3),
        **trace_fields,
    }
    if args.swap_checkpoint:
        summary["rolling_swap"] = args.swap_checkpoint
    if args.verify:
        summary["verify_mismatches"] = mismatches
    obs.record_serve(kind="fleet_drill", **{
        k: v for k, v in summary.items()
        if isinstance(v, (int, float, str))})
    print(json.dumps(summary))
    if lost:
        print(f"DRILL FAILED: {len(lost)} accepted request(s) lost: "
              + ", ".join(f"{r.rid}[{r.state}:{r.error}]"
                          for r in lost[:8]),
              file=sys.stderr, flush=True)
        return 1
    if mismatches:
        print(f"VERIFY FAILED: {mismatches} redriven/completed "
              "request(s) diverged from solo decode",
              file=sys.stderr, flush=True)
        return 1
    if burn_alerts:
        print("SLO BURN: " + ", ".join(
            f"{a.get('replica')}:{a.get('metric')} "
            f"(fast {a.get('burn_fast')}x, slow {a.get('burn_slow')}x)"
            for a in burn_alerts[:8]),
            file=sys.stderr, flush=True)
        return 1
    return 0


class _ReplicaLauncher:
    """The autoscaling supervisor's process-control half: ``launch()``
    spawns one more replica (same preset/seed/geometry as the seed
    fleet — the bit-identity contract survives scaling) and blocks
    until it listens; ``retire(name)`` SIGTERM-drains it.  The shared
    ``procs`` list keeps every process ever launched so the drill's
    epilogue can drain/merge them all."""

    def __init__(self, preset: str, args, fleet_dir: str,
                 procs: List[ReplicaProcess]):
        self.preset, self.args, self.fleet_dir = preset, args, fleet_dir
        self.procs = procs
        self._next = args.replicas
        self._lock = threading.Lock()

    def launch(self) -> ReplicaProcess:
        with self._lock:
            i = self._next
            self._next += 1
        args = self.args
        port = free_port()
        obs_dir = os.path.join(self.fleet_dir, "obs", f"replica{i}")
        run_dir = os.path.join(self.fleet_dir, f"replica{i}_run")
        env = dict(os.environ)
        env.pop("TORCHPRUNER_CHAOS", None)
        rep = ReplicaProcess(
            name=f"replica{i}", port=port,
            argv=replica_argv(self.preset, port, args, obs_dir, run_dir),
            env=env,
            log_path=os.path.join(self.fleet_dir, f"replica{i}.log"))
        rep.obs_dir = obs_dir
        rep.spawn()
        with self._lock:
            self.procs.append(rep)
        if not rep.wait_listening(timeout_s=args.startup_timeout_s):
            rep.kill9()
            raise RuntimeError(f"{rep.name} never started listening "
                               f"(see {rep.log_path})")
        return rep

    def retire(self, name: str) -> None:
        with self._lock:
            procs = list(self.procs)
        for p in procs:
            if p.name == name:
                p.drain(timeout_s=self.args.startup_timeout_s)
                return


def run_scenario(preset: str, args, fleet_dir: str,
                 chaos: FleetChaos) -> int:
    """The scenario replay / autoscale chaos drill: replay a committed
    workload scenario (digest-asserted) against the fleet with the
    SLO-driven supervisor closing the scale loop, then assert the
    robustness contract — zero accepted-request loss across scale-up
    AND drain-based scale-down, every scale decision ledgered, batch
    tier shed then resumed, interactive TTFT p99 within budget."""
    from torchpruner_tpu import obs
    from torchpruner_tpu.fleet.supervisor import (
        RUNGS,
        ScalePolicy,
        Supervisor,
        predict_replica_capacity,
    )
    from torchpruner_tpu.fleet.workload import (
        WorkloadReplayer,
        build_schedule,
        load_scenario,
        verify_schedule,
    )
    from torchpruner_tpu.serve.engine import vocab_of
    from torchpruner_tpu.serve.frontend import _resolve_model
    from torchpruner_tpu.serve.qos import TenantPolicy

    spec = load_scenario(args.scenario)
    schedule = build_schedule(spec)
    digest = verify_schedule(spec, schedule)
    obs.gauge_set("workload_planned_requests", len(schedule),
                  help="scenario schedule size (committed, "
                       "digest-pinned)")
    model, _params, _meta = _resolve_model(
        preset, smoke=args.smoke, seed=args.seed,
        checkpoint=args.checkpoint)
    if int(spec["vocab"]) > vocab_of(model):
        raise SystemExit(
            f"scenario vocab {spec['vocab']} exceeds the served "
            f"model's vocab {vocab_of(model)} — the committed prompt "
            f"ids would be out of range")
    tenants = spec.get("tenants") or {}
    args.tenants_json = json.dumps(tenants) if tenants else None
    # rung 1's shed set: the scenario's preemptible batch tier
    batch_tier = tuple(sorted(
        name for name, cfg in tenants.items()
        if TenantPolicy.from_dict(name, cfg).priority > 0))

    procs = spawn_fleet(preset, args, fleet_dir, chaos)
    plane = RequestPlane(os.path.join(fleet_dir, JOURNAL_FILENAME))
    router = FleetRouter(plane, procs, policy=_policy_of(args))
    trigger = _ChaosTrigger(chaos, procs)

    sup = None
    if args.autoscale:
        policy = ScalePolicy(
            min_replicas=args.replicas,
            max_replicas=args.max_replicas,
            queue_age_up_s=args.scale_up_age_s,
            queue_age_down_s=args.scale_down_age_s,
            cooldown_s=args.scale_cooldown_s,
            drain_timeout_s=args.startup_timeout_s,
            shed_tenants=batch_tier,
            pruned_checkpoint=args.degrade_checkpoint,
            restore_checkpoint=args.checkpoint)
        # capacity prediction BEFORE any launch: what the ledger says
        # one more replica should buy (best-effort, None on CPU-less
        # exotic models)
        capacity = predict_replica_capacity(
            model, n_slots=args.slots, max_len=args.max_len)
        launcher = _ReplicaLauncher(preset, args, fleet_dir, procs)
        sup = Supervisor(router, policy, launcher=launcher,
                         capacity=capacity)

    replayer = WorkloadReplayer.from_spec(router, spec,
                                          deadline_s=args.deadline_s)
    t0 = time.monotonic()

    def on_tick():
        router.tick()
        trigger(router)
        if sup is not None:
            sup.tick()

    try:
        router.check_health(force=True)
        rsum = replayer.run(timeout_s=args.drill_timeout_s,
                            on_tick=on_tick)
        # settle: keep ticking until the supervisor has recovered every
        # degradation rung and drained the surge capacity back down —
        # the drill's "reversible" half (scale_down + recover must both
        # land, or we time out and the asserts below fail loudly)
        if sup is not None:
            deadline = time.monotonic() + args.settle_timeout_s
            while time.monotonic() < deadline:
                on_tick()
                s = sup.summary()
                with router._lock:
                    n_views = len(router.views)
                if s["scale_downs"] >= 1 and s["rung"] == RUNGS[0] \
                        and n_views <= args.replicas \
                        and not sup._busy():
                    break
                time.sleep(0.02)
            sup.join(timeout_s=args.settle_timeout_s)
        tenant_table = router.tenant_summary()
    finally:
        router.close()
        exit_codes = {p.name: p.drain(timeout_s=args.startup_timeout_s)
                      for p in procs}
    wall = time.monotonic() - t0

    shards = merge_replica_shards(
        os.path.join(fleet_dir, "obs"), [p.obs_dir for p in procs])
    try:
        ts_merge = merge_timeseries(
            os.path.join(fleet_dir, "obs"), [p.obs_dir for p in procs])
    except Exception:
        ts_merge = {"streams": 0, "windows": 0}
    trace_fields = _finalize_tracing(os.path.join(fleet_dir, "obs"))
    # same epilogue as the drill: replica burns re-homed into the fleet
    # ledger (incident correlation included) — informational here, the
    # scenario's verdict stays with the robustness asserts below
    burn_alerts = _collect_burn_alerts(procs)

    records = plane.records()
    completed = [r for r in records if r.state == COMPLETED]
    lost = [r for r in records if r.state != COMPLETED]
    ssum = sup.summary() if sup is not None else {}
    summary = {
        "mode": "scenario",
        "scenario": rsum.scenario,
        "digest": rsum.digest,
        "replicas_min": args.replicas,
        "replicas_max": args.max_replicas,
        **{k: v for k, v in rsum.to_json().items()
           if k not in ("scenario", "digest")},
        "accepted": len(records),
        "completed": len(completed),
        "lost": len(lost),
        "redrives": sum(r.redrives for r in records),
        "replica_exit_codes": exit_codes,
        "shards_merged": sum(bool(v) for v in shards.values()),
        "ts_streams": ts_merge["streams"],
        "ts_windows": ts_merge["windows"],
        "slo_burn_alerts": len(burn_alerts),
        **_incident_counts(),
        "tenants": tenant_table,
        "wall_s": round(wall, 3),
        **trace_fields,
    }
    if sup is not None:
        summary["autoscale"] = ssum
    obs.record_serve(kind="scenario_drill", **{
        k: v for k, v in summary.items()
        if isinstance(v, (int, float, str))})
    print(json.dumps(summary))

    failures: List[str] = []
    if lost:
        failures.append(
            f"{len(lost)} accepted request(s) lost: "
            + ", ".join(f"{r.rid}[{r.state}:{r.error}]"
                        for r in lost[:8]))
    # batch-tier abandonment under a degrade rung is the ladder WORKING
    # (that tier is being shed on purpose); any other tenant abandoned
    # means admission control turned away traffic it must not
    hard_abandoned = {t or "(none)": n
                      for t, n in rsum.abandoned_by_tenant.items()
                      if t not in batch_tier}
    if hard_abandoned:
        failures.append(f"non-batch request(s) abandoned after "
                        f"exhausting their hedged-retry budget: "
                        f"{hard_abandoned}")
    if sup is not None:
        if ssum["scale_ups"] < 1:
            failures.append("no scale_up decision fired")
        if ssum["scale_downs"] < 1:
            failures.append("no scale_down landed (surge capacity "
                            "never drained back out)")
        if batch_tier and ssum["degrades"] < 1:
            failures.append("batch tier was never shed (no degrade "
                            "rung climbed)")
        if ssum["degrades"] and ssum["recovers"] < ssum["degrades"]:
            failures.append("degradation rung(s) never recovered "
                            f"(rung {ssum['rung']})")
        if ssum["errors"]:
            failures.append(f"supervisor errors: {ssum['errors'][:4]}")
    if args.assert_ttft_p99_ms > 0:
        interactive = [
            name for name, cfg in tenants.items()
            if TenantPolicy.from_dict(name, cfg).priority == 0]
        for name in interactive:
            row = tenant_table.get(name) or {}
            p99 = row.get("ttft_p99_s")
            if p99 is not None \
                    and p99 * 1e3 > args.assert_ttft_p99_ms:
                failures.append(
                    f"tenant {name!r} TTFT p99 {p99 * 1e3:.0f} ms "
                    f"exceeds the {args.assert_ttft_p99_ms:.0f} ms "
                    f"budget")
    if failures:
        for f in failures:
            print(f"SCENARIO DRILL FAILED: {f}", file=sys.stderr,
                  flush=True)
        return 1
    return 0


def _incident_counts() -> dict:
    """The fleet session's incident/anomaly tallies for the summary
    line (zeros without a session — e.g. unit tests calling the run_*
    helpers directly)."""
    from torchpruner_tpu import obs

    s = obs.get()
    out = {"incidents": 0, "anomalies": 0}
    if s is not None and s.incidents is not None:
        out["incidents"] = len(s.incidents.incidents)
    if s is not None and s.anomaly is not None:
        out["anomalies"] = s.anomaly.counts()["opened"]
    return out


def _collect_burn_alerts(procs) -> List[dict]:
    """Every replica's ledgered ``slo_burn`` records (serve/slo.py's
    multi-window burn-rate alerts), re-recorded into the FLEET session's
    ledger stamped with the replica name — so the merged fleet report
    carries the incident — and returned for the drill's verdict."""
    from torchpruner_tpu import obs
    from torchpruner_tpu.obs.ledger import LEDGER_FILENAME, load_ledger

    alerts: List[dict] = []
    for p in procs:
        path = os.path.join(p.obs_dir, LEDGER_FILENAME)
        if not os.path.exists(path):
            continue
        try:
            records = load_ledger(path)
        except Exception:
            continue
        for rec in records:
            if rec.get("event") == "serve" \
                    and rec.get("kind") == "slo_burn":
                fields = {k: v for k, v in rec.items()
                          if k not in ("event", "kind")}
                obs.record_serve(kind="slo_burn", replica=p.name,
                                 **fields)
                alerts.append({"replica": p.name, **fields})
    return alerts


def _verify_from_journal(model, params, completed,
                         max_len: int) -> int:
    """Re-decode every completed record's journal payload through solo
    ``generate()`` at the replicas' cache geometry and count token
    mismatches — works on greedy AND seed-pinned sampled requests
    because every replica serves identical weights/geometry (the
    redrive caveat: with non-identical replicas only greedy requests
    are re-verifiable)."""
    import jax
    import numpy as np

    from torchpruner_tpu.generate import generate

    mismatches = 0
    for rec in completed:
        p = rec.payload
        prompt = np.asarray(p["prompt_ids"], np.int32)
        want = generate(
            model, params, prompt[None], int(p["max_new"]),
            temperature=float(p.get("temperature") or 0.0),
            top_k=p.get("top_k"), top_p=p.get("top_p"),
            rng=jax.random.PRNGKey(int(p.get("seed") or 0)),
            max_len=max_len)
        got = np.asarray(rec.tokens or [], np.int32)
        if not np.array_equal(got, np.asarray(want)[0][:got.size]) \
                or got.size != int(p["max_new"]):
            # eos early-stop: accept a shorter stream only when the
            # solo replay stops at the same token
            solo = np.asarray(want)[0]
            if not (got.size and p.get("eos_id") is not None
                    and got[-1] == p["eos_id"]
                    and np.array_equal(got, solo[:got.size])):
                mismatches += 1
    return mismatches


def run_http(preset: str, args, fleet_dir: str,
             chaos: FleetChaos) -> int:
    """The fleet HTTP endpoint: accept → journal → route → answer."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from torchpruner_tpu.resilience.guards import PreemptionHandler

    procs = spawn_fleet(preset, args, fleet_dir, chaos)
    journal = os.path.join(fleet_dir, JOURNAL_FILENAME)
    if os.path.exists(journal):
        # a restarted endpoint REDRIVES its previous incarnation's
        # journal instead of clobbering it — the router-death half of
        # the completed-or-redrivable contract
        # retain_terminal bounds the journal: the long-running endpoint
        # keeps only the newest terminal records (flush cost must not
        # grow with lifetime traffic)
        plane = RequestPlane.load(journal, retain_terminal=512)
        redriven = plane.pending_depth
        if redriven:
            print(f"[fleet] journal reloaded: {redriven} non-terminal "
                  f"record(s) redriven", file=sys.stderr, flush=True)
    else:
        plane = RequestPlane(journal, retain_terminal=512)
    router = FleetRouter(plane, procs, policy=_policy_of(args))
    stop = threading.Event()

    def pump():
        while not stop.is_set():
            router.tick()
            time.sleep(0.02)

    from torchpruner_tpu.serve.frontend import http_json

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _json(self, code: int, payload: dict,
                  headers: Optional[dict] = None):
            http_json(self, code, payload, headers)

        def do_GET(self):
            if self.path == "/healthz":
                verdict = router.admission()
                self._json(
                    200 if verdict["accepting"] else verdict["code"],
                    {"ok": verdict["accepting"],
                     "reason": verdict["reason"],
                     "degraded": router.degraded()})
            elif self.path == "/stats":
                self._json(200, router.snapshot())
            else:
                self._json(404, {"error": "not found"})

        def do_POST(self):
            if self.path != "/v1/generate":
                self._json(404, {"error": "not found"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n))
                # validate the wire schema BEFORE the journal accepts
                # it: a malformed request must be an immediate 400, not
                # a journaled record that burns the whole retry budget
                # on replica 400s and lands in the LOSS counter
                probe = request_from_dict(payload)
                probe.sampling.validate(0)
            except (KeyError, TypeError, ValueError,
                    json.JSONDecodeError) as e:
                self._json(400, {"error": f"bad request: {e}"})
                return
            rec = router.submit(payload, deadline_s=args.deadline_s)
            if rec is None:
                verdict = router.admission()
                self._json(verdict["code"] or 503,
                           {"error": verdict["reason"] or "shed"},
                           headers={"Retry-After":
                                    verdict["retry_after_s"] or 1})
                return
            rec.wait(timeout=args.deadline_s + 5)
            if rec.state == COMPLETED:
                self._json(200, {"id": rec.rid, "state": "done",
                                 "tokens": rec.tokens,
                                 "served_by": rec.completed_by,
                                 "attempts": rec.attempts,
                                 "redrives": rec.redrives})
            else:
                self._json(504, {"id": rec.rid, "state": rec.state,
                                 "error": rec.error})

    server = ThreadingHTTPServer(("127.0.0.1", args.http), Handler)
    pump_t = threading.Thread(target=pump, daemon=True)
    pump_t.start()
    srv_t = threading.Thread(target=server.serve_forever, daemon=True)
    srv_t.start()
    print(f"fleet: routing {args.replicas} replicas on "
          f"http://127.0.0.1:{args.http} (POST /v1/generate, "
          f"GET /healthz /stats)", file=sys.stderr, flush=True)
    rc = 0
    try:
        with PreemptionHandler() as pre:
            while not pre.requested:
                time.sleep(0.2)
            print("[fleet] SIGTERM: draining", file=sys.stderr,
                  flush=True)
            deadline = time.monotonic() + args.deadline_s
            while not plane.all_terminal() \
                    and time.monotonic() < deadline:
                time.sleep(0.1)
    finally:
        stop.set()
        server.shutdown()
        router.close()
        for p in procs:
            p.drain(timeout_s=args.startup_timeout_s)
        merge_replica_shards(os.path.join(fleet_dir, "obs"),
                             [p.obs_dir for p in procs])
        try:
            merge_timeseries(os.path.join(fleet_dir, "obs"),
                             [p.obs_dir for p in procs])
        except Exception:
            pass
        trace_fields = _finalize_tracing(os.path.join(fleet_dir, "obs"))
        burn_alerts = _collect_burn_alerts(procs)
        print(json.dumps({"mode": "http", **router.snapshot(),
                          "slo_burn_alerts": len(burn_alerts),
                          **_incident_counts(), **trace_fields}),
              file=sys.stderr, flush=True)
    return rc


def _policy_of(args) -> RouterPolicy:
    return RouterPolicy(
        queue_bound=args.queue_bound,
        max_attempts=args.max_attempts,
        attempt_timeout_s=args.attempt_timeout_s,
        default_deadline_s=args.deadline_s,
        seed=args.seed,
        health_every_s=args.health_every_s,
        max_inflight_per_replica=args.inflight_per_replica)


def fleet_main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="torchpruner_tpu fleet",
        description="fault-tolerant multi-replica serving plane: "
                    "health-checked router over N serve replicas, "
                    "durable request journal, redrive on replica "
                    "death, degraded-mode admission, failover drills")
    p.add_argument("preset", help="preset/model name every replica "
                                  "serves (same seed ⇒ identical "
                                  "weights ⇒ redrive bit-identity)")
    p.add_argument("--checkpoint", metavar="DIR",
                   help="serve this checkpoint on every replica")
    p.add_argument("--replicas", type=int, default=3)
    p.add_argument("--slots", type=int, default=2)
    p.add_argument("--max-len", type=int, default=96)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--smoke", action="store_true")
    p.add_argument("--cpu", action="store_true")
    p.add_argument("--fleet-dir", default="logs/fleet",
                   help="journal + per-replica obs/run/log dirs + the "
                        "merged fleet obs dir live here")
    mode = p.add_mutually_exclusive_group(required=True)
    mode.add_argument("--synthetic", type=int, metavar="N",
                      help="failover drill: N open-loop Poisson "
                           "requests, JSON summary, exit 1 on any "
                           "accepted-request loss")
    mode.add_argument("--http", type=int, metavar="PORT",
                      help="serve the fleet HTTP endpoint")
    mode.add_argument("--scenario", metavar="JSON",
                      help="scenario replay drill: replay a committed "
                           "workload scenario (results/scenarios/) "
                           "against the fleet — digest-asserted "
                           "deterministic traffic, per-tenant QoS from "
                           "the spec, JSON summary, exit 1 on any "
                           "accepted-request loss (add --autoscale for "
                           "the supervisor chaos drill)")
    p.add_argument("--autoscale", action="store_true",
                   help="scenario: run the SLO-driven autoscaling "
                        "supervisor (scale on queue age / breach "
                        "fraction between --replicas and "
                        "--max-replicas, degradation ladder at max, "
                        "every decision ledgered before its effect)")
    p.add_argument("--max-replicas", type=int, default=4,
                   help="autoscale: replica ceiling (past it the "
                        "supervisor climbs the degradation ladder "
                        "instead)")
    p.add_argument("--scale-up-age-s", type=float, default=1.0,
                   help="autoscale: scale up when the oldest pending "
                        "request is older than this")
    p.add_argument("--scale-down-age-s", type=float, default=0.1,
                   help="autoscale: eligible to scale down only below "
                        "this queue age (plus an empty plane)")
    p.add_argument("--scale-cooldown-s", type=float, default=2.0,
                   help="autoscale: quiet period after every action")
    p.add_argument("--degrade-checkpoint", metavar="DIR",
                   help="autoscale: degradation-ladder rung 3 — "
                        "rolling-swap replicas to this PRUNED "
                        "checkpoint when shedding + tightening were "
                        "not enough (omit to skip the rung)")
    p.add_argument("--assert-ttft-p99-ms", type=float, default=0.0,
                   help="scenario: fail the drill when any INTERACTIVE "
                        "tenant's TTFT p99 exceeds this budget "
                        "(0 = no assertion)")
    p.add_argument("--settle-timeout-s", type=float, default=240.0,
                   help="autoscale: post-replay budget for recovery + "
                        "drain-based scale-down to land")
    p.add_argument("--rate", type=float, default=4.0,
                   help="drill: Poisson arrival rate (requests/s)")
    p.add_argument("--prompt-lens", default="4,8,6")
    p.add_argument("--max-new", default="8,5,12")
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--shared-prefixes", type=int, default=0, metavar="K",
                   help="drill: draw prompts from a pool of K shared "
                        "system prompts + random suffixes (--prompt-"
                        "lens become SUFFIX lengths) — the prefix-"
                        "affinity workload; 0 = fully random prompts")
    p.add_argument("--prefix-len", type=int, default=32,
                   help="drill: shared system-prompt length in tokens")
    p.add_argument("--sessions", type=int, default=0,
                   help="drill: tag requests with round-robin session "
                        "ids — the router's session-affinity signal")
    p.add_argument("--page-len", type=int, default=0,
                   help="per-replica KV page size (serve --page-len; "
                        "0 = lane-aligned default — note the default "
                        "can be a whole slot at small max-len, which "
                        "makes 16-token prefixes unshareable)")
    p.add_argument("--prefix-pages", type=int, default=0,
                   help="per-replica shared-prefix KV pool pages "
                        "(serve --prefix-pages on every replica; 0 = "
                        "sharing off)")
    p.add_argument("--prefill-chunk", type=int, default=0,
                   help="per-replica chunked-prefill width (serve "
                        "--prefill-chunk; 0 = auto with prefix pages)")
    p.add_argument("--prefill-cap", type=int, default=0,
                   help="per-replica per-step prefill-token budget "
                        "(serve --prefill-cap; 0 = uncapped)")
    p.add_argument("--verify", action="store_true",
                   help="drill: re-decode every completed request from "
                        "the journal through solo generate() and "
                        "assert token bit-identity (the redrive "
                        "correctness contract)")
    p.add_argument("--chaos", metavar="JSON",
                   help="fleet fault injection, e.g. "
                        "'{\"kill_replica_at_step\": 8}' (SIGKILL), "
                        "hang_replica_at_step (SIGSTOP), "
                        "slow_replica_ms (per-step stall), "
                        "replica_index")
    p.add_argument("--swap-checkpoint", metavar="DIR",
                   help="drill: rolling hot-swap every replica to this "
                        "checkpoint once --swap-after dispatches "
                        "happened (the fleet upgrade loop)")
    p.add_argument("--swap-after", type=int, default=4)
    p.add_argument("--queue-bound", type=int, default=64,
                   help="router pending-queue bound (shed past it; "
                        "tightened while degraded)")
    p.add_argument("--replica-queue-bound", type=int, default=8,
                   help="per-replica scheduler queue bound (the serve "
                        "--queue-bound each replica runs with)")
    p.add_argument("--deadline-s", type=float, default=300.0,
                   help="per-request deadline budget")
    p.add_argument("--max-attempts", type=int, default=10)
    p.add_argument("--attempt-timeout-s", type=float, default=90.0)
    p.add_argument("--health-every-s", type=float, default=0.25)
    p.add_argument("--inflight-per-replica", type=int, default=4)
    p.add_argument("--drill-timeout-s", type=float, default=900.0)
    p.add_argument("--startup-timeout-s", type=float, default=300.0)
    p.add_argument("--slo-ttft-p99-ms", type=float, default=None,
                   help="forwarded to every replica (their /healthz "
                        "flips to slo_breach on episodes — the "
                        "router's degraded-admission signal)")
    p.add_argument("--slo-token-p99-ms", type=float, default=None)
    p.add_argument("--slo-queue-p99-ms", type=float, default=None,
                   help="replica queue-age-at-admission p99 SLO (ms); "
                        "joins the burn-rate evaluation like the "
                        "ttft/token thresholds")
    p.add_argument("--trace-sample-every", type=int, default=None,
                   metavar="N",
                   help="request-trace exemplar policy on the router "
                        "AND every replica (obs.reqtrace): full stage "
                        "detail for 1-in-N requests plus the slowest-K "
                        "per window; default 1 (eager full tracing) "
                        "for --synthetic drills, 16 for --http")
    p.add_argument("--no-obs", action="store_true")
    args = p.parse_args(argv)
    if args.trace_sample_every is None:
        # the failover drill's acceptance contract needs EVERY
        # request's cross-process waterfall; the long-running endpoint
        # AND the scenario drill sample (a flash crowd must not write
        # a stage line per shed)
        args.trace_sample_every = 1 if args.synthetic is not None else 16

    chaos = FleetChaos.from_any(args.chaos)
    fleet_dir = os.path.abspath(args.fleet_dir)
    os.makedirs(fleet_dir, exist_ok=True)

    if args.cpu:
        # the driver itself touches jax (model init for synthetic
        # vocab + --verify replays) — pin it like the replicas
        import jax

        jax.config.update("jax_platforms", "cpu")

    from torchpruner_tpu import obs
    from torchpruner_tpu.obs import reqtrace

    reqtrace.configure(sample_every=args.trace_sample_every)
    session = None
    if not args.no_obs:
        session = obs.configure(os.path.join(fleet_dir, "obs"))
        obs.annotate_run(experiment=f"fleet:{args.preset}", kind="fleet",
                         model=args.preset, replicas=args.replicas)
    try:
        if args.http is not None:
            return run_http(args.preset, args, fleet_dir, chaos)
        if args.scenario is not None:
            return run_scenario(args.preset, args, fleet_dir, chaos)
        return run_drill(args.preset, args, fleet_dir, chaos)
    finally:
        if session is not None:
            obs.shutdown(print_to=sys.stderr)
            # the session's own export wrote the ROUTER-only trace;
            # overwrite it with the ONE merged fleet trace: every
            # process's span flame on its own pid + the per-request
            # cross-process waterfalls (clock-offset aligned)
            try:
                from torchpruner_tpu.fleet.report import (
                    write_fleet_trace,
                )

                write_fleet_trace(os.path.join(fleet_dir, "obs"))
            except Exception as e:  # the trace must never fail the run
                print(f"[fleet] merged trace export failed: {e}",
                      file=sys.stderr)
            # re-merge the fleet time-series: the router's own final
            # window only lands at session close, so the in-drill merge
            # missed it
            try:
                merge_timeseries(os.path.join(fleet_dir, "obs"))
            except Exception:
                pass
            print(f"fleet telemetry written to "
                  f"{os.path.join(fleet_dir, 'obs')} (merged "
                  f"trace.json: open in ui.perfetto.dev)",
                  file=sys.stderr)


if __name__ == "__main__":
    sys.exit(fleet_main())

"""Deterministic scenario workloads: the fleet's committed traffic
library.

A **scenario** is a named, committed JSON spec (``results/scenarios/``)
describing production-shaped traffic as composable pieces:

- **phases** — back-to-back time windows, each with an arrival ``rate``
  (requests/s; a ``[r0, r1]`` pair ramps linearly across the phase —
  diurnal ramps and 10× flash crowds are both just phases) and a
  ``mix`` of traffic classes.
- **classes** — request shapes: heavy-tail prompt/output length lists
  (cycled deterministically), optional session reuse (``sessions`` →
  round-robin session ids, the router's prefix-affinity signal), each
  bound to a QoS **tenant**.
- **tenants** — the ``serve.qos.TenantPolicy`` table the replicas run
  (priority class, token bucket, KV-page quota) — committed WITH the
  traffic so a scenario is one reproducible contract, not two halves.

Determinism is the point: ``build_schedule`` derives every arrival
time (non-homogeneous Poisson via thinning), class pick, prompt id and
session id from ONE ``numpy`` generator seeded by the spec, and the
spec commits a sha256 **digest** of the resulting schedule.  Replay
asserts the digest, so every serving PR is benched against bit-equal
traffic — the apples-to-apples comparator next to the PR 14 reqtrace
budgets and PR 17 steady-state windows.

The **replayer** drives a :class:`~torchpruner_tpu.fleet.router.
FleetRouter` open-loop (arrivals never wait for completions) with
hedged retries that HONOR Retry-After: a shed submission is re-tried
after ``max(Retry-After, deterministic backoff)`` up to a bounded
attempt count, never sooner — the well-behaved-client contract the
router's 429/503 + Retry-After admission is designed for.
"""

from __future__ import annotations

import hashlib
import heapq
import json
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from torchpruner_tpu import obs
from torchpruner_tpu.serve.qos import TenantPolicy

SCENARIO_VERSION = 1

_SPEC_KEYS = {"version", "name", "seed", "vocab", "digest", "tenants",
              "classes", "phases", "retry", "notes"}
_CLASS_KEYS = {"tenant", "prompt_lens", "max_new", "sessions",
               "temperature"}
_PHASE_KEYS = {"name", "duration_s", "rate", "mix"}
_RETRY_KEYS = {"max_attempts", "base_delay_s", "max_delay_s",
               "hedge_after_s"}


def load_scenario(path: str) -> dict:
    """Read + validate a committed scenario spec (unknown keys rejected
    — the config-typo guard every other committed config here uses)."""
    with open(path) as f:
        spec = json.load(f)
    return validate_scenario(spec)


def validate_scenario(spec: dict) -> dict:
    unknown = set(spec) - _SPEC_KEYS
    if unknown:
        raise ValueError(f"unknown scenario key(s): {sorted(unknown)}")
    if int(spec.get("version", 0)) != SCENARIO_VERSION:
        raise ValueError(f"scenario version {spec.get('version')!r} != "
                         f"{SCENARIO_VERSION}")
    for req in ("name", "seed", "vocab", "classes", "phases"):
        if req not in spec:
            raise ValueError(f"scenario missing {req!r}")
    for name, cfg in (spec.get("tenants") or {}).items():
        TenantPolicy.from_dict(name, cfg)  # raises on bad policy
    for cname, c in spec["classes"].items():
        unknown = set(c) - _CLASS_KEYS
        if unknown:
            raise ValueError(f"class {cname!r}: unknown key(s) "
                             f"{sorted(unknown)}")
        if not c.get("prompt_lens") or not c.get("max_new"):
            raise ValueError(f"class {cname!r}: prompt_lens and "
                             f"max_new must be non-empty lists")
        tenant = c.get("tenant")
        if tenant is not None and tenant not in (spec.get("tenants")
                                                 or {}):
            raise ValueError(f"class {cname!r}: unknown tenant "
                             f"{tenant!r}")
    for i, p in enumerate(spec["phases"]):
        unknown = set(p) - _PHASE_KEYS
        if unknown:
            raise ValueError(f"phase {i}: unknown key(s) "
                             f"{sorted(unknown)}")
        if float(p.get("duration_s", 0)) <= 0:
            raise ValueError(f"phase {i}: duration_s must be > 0")
        for cname in (p.get("mix") or {}):
            if cname not in spec["classes"]:
                raise ValueError(f"phase {i}: mix names unknown class "
                                 f"{cname!r}")
    unknown = set(spec.get("retry") or {}) - _RETRY_KEYS
    if unknown:
        raise ValueError(f"retry: unknown key(s) {sorted(unknown)}")
    return spec


def _phase_rates(phase: dict) -> tuple:
    r = phase["rate"]
    if isinstance(r, (list, tuple)):
        r0, r1 = float(r[0]), float(r[1])
    else:
        r0 = r1 = float(r)
    if r0 < 0 or r1 < 0 or (r0 == 0 and r1 == 0):
        raise ValueError(f"phase rate {r!r} must be positive")
    return r0, r1


@dataclass(frozen=True)
class ScheduledRequest:
    """One planned arrival: offset from scenario start + the wire
    payload (``request_from_dict`` schema, tenant included)."""

    t: float
    cls: str
    tenant: Optional[str]
    payload: dict


def build_schedule(spec: dict) -> List[ScheduledRequest]:
    """Expand a scenario into its concrete arrival schedule.  Pure
    function of the spec: one seeded generator drives phase thinning,
    class picks and prompt ids in a FIXED visitation order, so the
    same spec always yields the same schedule (the digest contract)."""
    rng = np.random.default_rng(int(spec["seed"]))
    vocab = int(spec["vocab"])
    classes = spec["classes"]
    out: List[ScheduledRequest] = []
    t_base = 0.0
    counters = {c: 0 for c in classes}  # per-class cycling index
    for phase in spec["phases"]:
        dur = float(phase["duration_s"])
        r0, r1 = _phase_rates(phase)
        mix = phase.get("mix") or {}
        names = sorted(mix)
        weights = np.asarray([float(mix[n]) for n in names], float)
        if not names or weights.sum() <= 0:
            raise ValueError(f"phase {phase.get('name')!r}: empty mix")
        weights = weights / weights.sum()
        # non-homogeneous Poisson via thinning at the phase's peak rate
        rmax = max(r0, r1)
        t = 0.0
        while True:
            t += float(rng.exponential(1.0 / rmax))
            if t >= dur:
                break
            rate_t = r0 + (r1 - r0) * (t / dur)
            if float(rng.uniform()) > rate_t / rmax:
                continue
            cname = names[int(rng.choice(len(names), p=weights))]
            c = classes[cname]
            i = counters[cname]
            counters[cname] = i + 1
            plen = int(c["prompt_lens"][i % len(c["prompt_lens"])])
            ids = rng.integers(0, vocab, size=plen)
            sessions = int(c.get("sessions", 0))
            payload = {
                "prompt_ids": [int(x) for x in ids],
                "max_new": int(c["max_new"][i % len(c["max_new"])]),
                "temperature": float(c.get("temperature", 0.0)),
                "seed": int(spec["seed"]) + len(out),
            }
            if c.get("tenant") is not None:
                payload["tenant"] = c["tenant"]
            if sessions:
                payload["session_id"] = f"{cname}-s{i % sessions}"
            out.append(ScheduledRequest(
                t=round(t_base + t, 9), cls=cname,
                tenant=c.get("tenant"), payload=payload))
        t_base += dur
    out.sort(key=lambda s: s.t)
    return out


def schedule_digest(schedule: List[ScheduledRequest]) -> str:
    """sha256 over the schedule's canonical JSON — arrival times,
    classes and full payloads — the replay-determinism assertion."""
    canon = [[s.t, s.cls, s.tenant,
              {k: s.payload[k] for k in sorted(s.payload)}]
             for s in schedule]
    raw = json.dumps(canon, separators=(",", ":"), sort_keys=True)
    return hashlib.sha256(raw.encode()).hexdigest()


def verify_schedule(spec: dict,
                    schedule: List[ScheduledRequest]) -> str:
    """Assert the built schedule matches the spec's committed digest
    (when present) and return the digest.  A mismatch means the
    generator or the spec changed — either way cross-PR comparisons
    just broke, loudly."""
    digest = schedule_digest(schedule)
    want = spec.get("digest")
    if want and want != digest:
        raise ValueError(
            f"scenario {spec.get('name')!r}: schedule digest {digest} "
            f"!= committed {want} (same spec + seed must replay the "
            f"same traffic)")
    return digest


@dataclass
class ReplaySummary:
    """What the replayer observed (the drill summary's workload half)."""

    scenario: str = ""
    digest: str = ""
    planned: int = 0
    submitted: int = 0
    accepted: int = 0
    shed: int = 0
    retries: int = 0
    hedges: int = 0
    abandoned: int = 0
    wall_s: float = 0.0
    by_tenant: Dict[str, int] = field(default_factory=dict)
    #: tenant ("" = untenanted) -> abandoned count; the drill verdict
    #: tolerates batch-tier abandonment (shedding that tier IS the
    #: degradation ladder working) but fails on any other tenant's
    abandoned_by_tenant: Dict[str, int] = field(default_factory=dict)

    def to_json(self) -> dict:
        return dict(self.__dict__)


class WorkloadReplayer:
    """Open-loop scenario replay against a fleet router.

    Arrival times come from the schedule (never from completions).  A
    shed submission retries after ``max(Retry-After, deterministic
    backoff)`` for up to ``max_attempts`` total tries, then counts as
    abandoned (``workload_abandoned_total`` — the operator's signal
    that admission control turned clients away for good).  With
    ``hedge_after_s > 0``, an accepted record still non-terminal after
    that long gets ONE duplicate submission (the plane's idempotent
    completion drops whichever result lands second).
    """

    def __init__(self, router, schedule: List[ScheduledRequest], *,
                 deadline_s: float = 60.0, max_attempts: int = 4,
                 base_delay_s: float = 0.05, max_delay_s: float = 2.0,
                 hedge_after_s: float = 0.0, seed: int = 0):
        self.router = router
        self.schedule = schedule
        self.deadline_s = float(deadline_s)
        self.max_attempts = max(1, int(max_attempts))
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.hedge_after_s = float(hedge_after_s)
        self._rng = np.random.default_rng(seed)
        self.summary = ReplaySummary(planned=len(schedule))
        #: (due_t, tiebreak, attempt_no, ScheduledRequest) retry heap
        self._retries: List[tuple] = []
        self._tie = 0
        #: accepted records still eligible for one hedge:
        #: [(accepted_rel_t, rec, sched)]
        self._hedgeable: List[tuple] = []

    @classmethod
    def from_spec(cls, router, spec: dict, *,
                  deadline_s: float = 60.0) -> "WorkloadReplayer":
        schedule = build_schedule(spec)
        digest = verify_schedule(spec, schedule)
        r = spec.get("retry") or {}
        rep = cls(router, schedule, deadline_s=deadline_s,
                  max_attempts=int(r.get("max_attempts", 4)),
                  base_delay_s=float(r.get("base_delay_s", 0.05)),
                  max_delay_s=float(r.get("max_delay_s", 2.0)),
                  hedge_after_s=float(r.get("hedge_after_s", 0.0)),
                  seed=int(spec["seed"]) ^ 0x5EED)
        rep.summary.scenario = str(spec.get("name", ""))
        rep.summary.digest = digest
        return rep

    # -- submission ----------------------------------------------------------

    def _backoff_s(self, attempt_no: int) -> float:
        base = min(self.max_delay_s,
                   self.base_delay_s * (2 ** (attempt_no - 1)))
        return base * (0.5 + float(self._rng.uniform()))

    def _try_submit(self, sched: ScheduledRequest, attempt_no: int,
                    now: float, *, hedge: bool = False) -> None:
        self.summary.submitted += 1
        obs.inc("workload_submitted_total",
                help="scenario submissions offered to the router "
                     "(retries and hedges included)")
        rec = self.router.submit(sched.payload,
                                 deadline_s=self.deadline_s)
        if rec is not None:
            self.summary.accepted += 1
            if sched.tenant:
                self.summary.by_tenant[sched.tenant] = \
                    self.summary.by_tenant.get(sched.tenant, 0) + 1
            if self.hedge_after_s > 0 and not hedge:
                self._hedgeable.append((now, rec, sched))
            return
        self.summary.shed += 1
        obs.inc("workload_shed_total",
                help="scenario submissions the router shed (hedged "
                     "retry follows while attempts remain)")
        if hedge:
            return  # a hedge is opportunistic — never retried
        if attempt_no >= self.max_attempts:
            self.summary.abandoned += 1
            key = sched.tenant or ""
            self.summary.abandoned_by_tenant[key] = \
                self.summary.abandoned_by_tenant.get(key, 0) + 1
            obs.inc("workload_abandoned_total",
                    help="scenario requests abandoned after exhausting "
                         "their hedged-retry budget")
            return
        # honor Retry-After: never knock again sooner than the router
        # asked, plus deterministic jittered backoff
        verdict = self.router.admission()
        delay = max(float(verdict.get("retry_after_s", 0)),
                    self._backoff_s(attempt_no))
        self.summary.retries += 1
        obs.inc("workload_retries_total",
                help="hedged retries of shed submissions (delayed by "
                     "max(Retry-After, jittered backoff))")
        self._tie += 1
        heapq.heappush(self._retries,
                       (now + delay, self._tie, attempt_no + 1, sched))

    def _pump_hedges(self, now: float) -> None:
        if self.hedge_after_s <= 0 or not self._hedgeable:
            return
        keep = []
        for t_acc, rec, sched in self._hedgeable:
            if rec.terminal():
                continue
            if now - t_acc >= self.hedge_after_s:
                self.summary.hedges += 1
                obs.inc("workload_hedges_total",
                        help="duplicate submissions of slow accepted "
                             "requests (idempotent completion keeps "
                             "exactly one result)")
                self._try_submit(sched, self.max_attempts, now,
                                 hedge=True)
            else:
                keep.append((t_acc, rec, sched))
        self._hedgeable = keep

    # -- the loop ------------------------------------------------------------

    def run(self, *, timeout_s: float = 300.0,
            on_tick: Optional[Callable[[], None]] = None,
            poll_s: float = 0.01,
            drain: bool = True) -> ReplaySummary:
        """Replay the whole schedule.  ``on_tick`` runs once per loop
        (the drill wires ``router.tick`` + ``supervisor.tick`` here);
        with ``drain`` the loop also waits for every accepted record
        to reach a terminal state before returning."""
        obs.inc("workload_requests_total", n=len(self.schedule),
                help="scenario arrivals planned (the committed "
                     "schedule's size)")
        t0 = time.monotonic()
        i, n = 0, len(self.schedule)
        while True:
            now = time.monotonic() - t0
            while i < n and self.schedule[i].t <= now:
                self._try_submit(self.schedule[i], 1, now)
                i += 1
            while self._retries and self._retries[0][0] <= now:
                _, _, attempt_no, sched = heapq.heappop(self._retries)
                self._try_submit(sched, attempt_no, now)
            self._pump_hedges(now)
            if on_tick is not None:
                on_tick()
            done_feeding = i >= n and not self._retries \
                and not self._hedgeable
            if done_feeding and (not drain
                                 or (self.router.plane.all_terminal()
                                     and self.router.plane.pending_depth
                                     == 0)):
                break
            if now > timeout_s:
                break
            time.sleep(poll_s)
        self.summary.wall_s = round(time.monotonic() - t0, 3)
        return self.summary

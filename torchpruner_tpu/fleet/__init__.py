"""``torchpruner_tpu.fleet`` — the fault-tolerant multi-replica
serving plane (ROADMAP item 2's composition refactor).

One engine serves one chip group; a fleet serves traffic.  This package
splits the TRANSPORT-AGNOSTIC request plane out of the engine-side
scheduler and composes the existing subsystems into a plane where a
``kill -9``'d replica is a non-event:

- :class:`~torchpruner_tpu.fleet.plane.RequestPlane` — durable request
  records in an atomic journal: every ACCEPTED request is either
  completed or redrivable, by construction.
- :class:`~torchpruner_tpu.fleet.replica.ReplicaClient` /
  :class:`~torchpruner_tpu.fleet.replica.ReplicaProcess` — the HTTP
  view of one serve replica (generate / healthz readiness states /
  stats gauges / swap) + subprocess lifecycle (spawn, kill -9,
  SIGSTOP "hang", SIGTERM drain).
- :class:`~torchpruner_tpu.fleet.router.FleetRouter` — health-checked
  least-loaded dispatch over the live ``kv_page_occupancy`` /
  ``slot_utilization`` gauges, per-request deadline budgets with
  bounded deterministic-jitter retries
  (``resilience.retry.with_retries``), hedged redrive of a dead
  replica's journaled queue, degraded-mode admission (bounded queue,
  SLO-tightened, 429/503 + Retry-After), rolling checkpoint hot-swap.
- :mod:`~torchpruner_tpu.fleet.report` — every replica's obs shard
  merged into ONE fleet-wide report (PR 5 aggregation).
- :mod:`~torchpruner_tpu.fleet.workload` — deterministic scenario
  library: committed JSON specs (diurnal ramps, flash crowds,
  heavy-tail length mixes, session reuse) compiled to a digest-pinned
  schedule and replayed open-loop with Retry-After-honoring hedged
  retries, so every serving PR is judged on the same traffic.
- :class:`~torchpruner_tpu.fleet.supervisor.Supervisor` — SLO-driven
  autoscaling (cost-model capacity prediction before launch, ledgered
  decisions before effects, drain-then-remove scale-down, graceful
  degradation ladder down to a pruned-checkpoint rolling swap).
- ``python -m torchpruner_tpu fleet <preset>``
  (:mod:`~torchpruner_tpu.fleet.frontend`) — the endpoint and the
  kill-9 failover / autoscale chaos drills CI runs.
"""

from torchpruner_tpu.fleet.plane import (
    ACCEPTED,
    COMPLETED,
    DISPATCHED,
    FAILED,
    PlaneRecord,
    RequestPlane,
)
from torchpruner_tpu.fleet.replica import (
    ReplicaBusy,
    ReplicaClient,
    ReplicaDown,
    ReplicaError,
    ReplicaProcess,
    ReplicaRejected,
    ReplicaTimeout,
    free_port,
)
from torchpruner_tpu.fleet.report import merge_replica_shards
from torchpruner_tpu.fleet.router import (
    FleetRouter,
    ReplicaView,
    RouterPolicy,
)
from torchpruner_tpu.fleet.supervisor import (
    ScalePolicy,
    Supervisor,
    predict_replica_capacity,
)
from torchpruner_tpu.fleet.workload import (
    ScheduledRequest,
    WorkloadReplayer,
    build_schedule,
    load_scenario,
    schedule_digest,
    verify_schedule,
)

__all__ = [
    "ACCEPTED", "DISPATCHED", "COMPLETED", "FAILED",
    "PlaneRecord", "RequestPlane",
    "ReplicaClient", "ReplicaProcess", "ReplicaError", "ReplicaDown",
    "ReplicaTimeout", "ReplicaBusy", "ReplicaRejected", "free_port",
    "FleetRouter", "RouterPolicy", "ReplicaView",
    "merge_replica_shards",
    "ScalePolicy", "Supervisor", "predict_replica_capacity",
    "ScheduledRequest", "WorkloadReplayer", "build_schedule",
    "load_scenario", "schedule_digest", "verify_schedule",
]

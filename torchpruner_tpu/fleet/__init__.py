"""``torchpruner_tpu.fleet`` — the fault-tolerant multi-replica
serving plane (ROADMAP item 2's composition refactor).

One engine serves one chip group; a fleet serves traffic.  This package
splits the TRANSPORT-AGNOSTIC request plane out of the engine-side
scheduler and composes the existing subsystems into a plane where a
``kill -9``'d replica is a non-event:

- :class:`~torchpruner_tpu.fleet.plane.RequestPlane` — durable request
  records in an atomic journal: every ACCEPTED request is either
  completed or redrivable, by construction.
- :class:`~torchpruner_tpu.fleet.replica.ReplicaClient` /
  :class:`~torchpruner_tpu.fleet.replica.ReplicaProcess` — the HTTP
  view of one serve replica (generate / healthz readiness states /
  stats gauges / swap) + subprocess lifecycle (spawn, kill -9,
  SIGSTOP "hang", SIGTERM drain).
- :class:`~torchpruner_tpu.fleet.router.FleetRouter` — health-checked
  least-loaded dispatch over the live ``kv_page_occupancy`` /
  ``slot_utilization`` gauges, per-request deadline budgets with
  bounded deterministic-jitter retries
  (``resilience.retry.with_retries``), hedged redrive of a dead
  replica's journaled queue, degraded-mode admission (bounded queue,
  SLO-tightened, 429/503 + Retry-After), rolling checkpoint hot-swap.
- :mod:`~torchpruner_tpu.fleet.report` — every replica's obs shard
  merged into ONE fleet-wide report (PR 5 aggregation).
- ``python -m torchpruner_tpu fleet <preset>``
  (:mod:`~torchpruner_tpu.fleet.frontend`) — the endpoint and the
  kill-9 failover drill CI runs.
"""

from torchpruner_tpu.fleet.plane import (
    ACCEPTED,
    COMPLETED,
    DISPATCHED,
    FAILED,
    PlaneRecord,
    RequestPlane,
)
from torchpruner_tpu.fleet.replica import (
    ReplicaBusy,
    ReplicaClient,
    ReplicaDown,
    ReplicaError,
    ReplicaProcess,
    ReplicaRejected,
    ReplicaTimeout,
    free_port,
)
from torchpruner_tpu.fleet.report import merge_replica_shards
from torchpruner_tpu.fleet.router import (
    FleetRouter,
    ReplicaView,
    RouterPolicy,
)

__all__ = [
    "ACCEPTED", "DISPATCHED", "COMPLETED", "FAILED",
    "PlaneRecord", "RequestPlane",
    "ReplicaClient", "ReplicaProcess", "ReplicaError", "ReplicaDown",
    "ReplicaTimeout", "ReplicaBusy", "ReplicaRejected", "free_port",
    "FleetRouter", "RouterPolicy", "ReplicaView",
    "merge_replica_shards",
]

"""Fleet-wide observability: merge the replicas' metric shards.

Each replica is its own process with its own obs dir, so each writes a
``metrics.shard0.json`` at close (PR 5's cross-host aggregation path —
there, process index distinguishes shards; here every replica is a
process 0 of its own little world).  The fleet driver re-homes those
shards into ITS obs dir under distinct indices before its own session
closes, so the ordinary ``obs.aggregate`` merge produces ONE fleet-wide
``metrics.prom`` / ``report.json``: serve histograms bucket-merged
across replicas, counters summed, gauges max-with-min-companion — plus
the router's own ``fleet_*`` counters riding the same registry.

A ``kill -9``'d replica never reaches its session close and therefore
ships no shard; the merge reports it missing instead of failing — the
fleet report is the SURVIVORS' merged view plus the router's account of
the death (``fleet_failover_total`` / ``fleet_redrive_total``).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from torchpruner_tpu.obs.aggregate import shard_path
from torchpruner_tpu.resilience.manifest import atomic_write_json


def merge_replica_shards(fleet_obs_dir: str,
                         replica_obs_dirs: List[str]) -> Dict[str, bool]:
    """Re-home each replica's ``metrics.shard0.json`` into
    ``fleet_obs_dir`` as ``metrics.shard<i+1>.json`` (index 0 is the
    fleet session's own registry).  Returns ``{replica_dir: present}``
    — call BEFORE ``obs.shutdown()`` so the fleet session's close
    merges what landed."""
    out: Dict[str, bool] = {}
    for i, rep_dir in enumerate(replica_obs_dirs):
        src = shard_path(rep_dir, 0)
        present = os.path.exists(src)
        out[rep_dir] = present
        if not present:  # a kill -9'd replica writes no shard
            continue
        try:
            with open(src) as f:
                shard = json.load(f)
        except (OSError, json.JSONDecodeError):
            out[rep_dir] = False
            continue
        shard["process_index"] = i + 1
        atomic_write_json(shard_path(fleet_obs_dir, i + 1), shard,
                          indent=None)
    return out


def replica_summary_line(log_path: str) -> Optional[dict]:
    """The last JSON line a serve front end printed (its run summary),
    scraped from the replica's captured output — best-effort."""
    try:
        with open(log_path, "rb") as f:
            lines = f.read().decode(errors="replace").splitlines()
    except OSError:
        return None
    for line in reversed(lines):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None

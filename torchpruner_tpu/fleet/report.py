"""Fleet-wide observability: shard merging + full trace assembly.

Each replica is its own process with its own obs dir, so each writes a
``metrics.shard0.json`` at close (PR 5's cross-host aggregation path —
there, process index distinguishes shards; here every replica is a
process 0 of its own little world).  The fleet driver re-homes those
shards into ITS obs dir under distinct indices before its own session
closes, so the ordinary ``obs.aggregate`` merge produces ONE fleet-wide
``metrics.prom`` / ``report.json``: serve histograms bucket-merged
across replicas, counters summed, gauges max-with-min-companion — plus
the router's own ``fleet_*`` counters riding the same registry.

A ``kill -9``'d replica never reaches its session close and therefore
ships no shard; the merge reports it missing instead of failing — the
fleet report is the SURVIVORS' merged view plus the router's account of
the death (``fleet_failover_total`` / ``fleet_redrive_total``).

On top of the metric shards, this module assembles the fleet's
**distributed request traces**: every process's ``events.jsonl`` —
including the kill -9'd replica's, which flushed per line and so keeps
every stage event up to the SIGKILL — is aligned onto the router's
clock (offsets estimated from the health monitor's request/response
timestamps, emitted as ``clock_offset`` events) and merged into ONE
Perfetto ``trace.json``: the span flame of each process on its own pid
plus per-request waterfall tracks whose rows hop router → replica
(→ survivor on a redrive).  :func:`trace_summary` is the drill's
contiguity verdict (every completed request cross-process, redriven
requests showing both attempts) and :func:`slowest_exemplars` feeds
``obs report``'s exemplar waterfalls.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, List, Optional

from torchpruner_tpu.obs import trace_export
from torchpruner_tpu.obs.aggregate import shard_path
from torchpruner_tpu.resilience.manifest import atomic_write_json


def merge_replica_shards(fleet_obs_dir: str,
                         replica_obs_dirs: List[str]) -> Dict[str, bool]:
    """Re-home each replica's ``metrics.shard0.json`` into
    ``fleet_obs_dir`` as ``metrics.shard<i+1>.json`` (index 0 is the
    fleet session's own registry).  Returns ``{replica_dir: present}``
    — call BEFORE ``obs.shutdown()`` so the fleet session's close
    merges what landed."""
    out: Dict[str, bool] = {}
    for i, rep_dir in enumerate(replica_obs_dirs):
        src = shard_path(rep_dir, 0)
        present = os.path.exists(src)
        out[rep_dir] = present
        if not present:  # a kill -9'd replica writes no shard
            continue
        try:
            with open(src) as f:
                shard = json.load(f)
        except (OSError, json.JSONDecodeError):
            out[rep_dir] = False
            continue
        shard["process_index"] = i + 1
        atomic_write_json(shard_path(fleet_obs_dir, i + 1), shard,
                          indent=None)
    return out


def clock_offsets_of(router_events: List[dict]) -> Dict[str, float]:
    """Per-replica clock offsets (replica clock − router clock) from
    the ``clock_offset`` events the router's health monitor emitted —
    LAST wins (the monitor re-emits on real changes, so the last is the
    freshest estimate)."""
    offsets: Dict[str, float] = {}
    for ev in router_events:
        if ev.get("event") == "clock_offset" and ev.get("replica"):
            offsets[str(ev["replica"])] = float(ev.get("offset_s") or 0.0)
    return offsets


# -- distributed trace assembly ----------------------------------------------


def replica_obs_dirs_of(fleet_obs_dir: str) -> List[str]:
    """The per-replica obs dirs the fleet driver spawned under its own
    obs dir (``replica<i>/``), in SPAWN order — numeric on the index
    suffix, so ``replica10`` sorts after ``replica9`` and the stream
    pids keep matching the re-homed metric-shard indices."""

    def index_of(d: str) -> int:
        tail = os.path.basename(os.path.normpath(d))[len("replica"):]
        try:
            return int(tail)
        except ValueError:
            return 1 << 30

    return sorted(
        (d for d in glob.glob(os.path.join(fleet_obs_dir, "replica*"))
         if os.path.isdir(d)),
        key=lambda d: (index_of(d), d))


def collect_streams(fleet_obs_dir: str,
                    replica_obs_dirs: Optional[List[str]] = None
                    ) -> List[dict]:
    """Every fleet process's parsed event stream with its trace
    placement: the router (the fleet session itself) on pid 0, each
    replica on pid i+1 (matching its re-homed metric-shard index), each
    replica's clock shifted onto the router's by the LAST offset the
    health monitor estimated for it (``clock_offset`` events in the
    router stream; 0 when none landed — e.g. a replica that died before
    its first probe answered)."""
    from torchpruner_tpu.utils.profiling import load_span_events

    if replica_obs_dirs is None:
        replica_obs_dirs = replica_obs_dirs_of(fleet_obs_dir)
    router_path = os.path.join(fleet_obs_dir, "events.jsonl")
    router_events = (load_span_events(router_path)
                     if os.path.exists(router_path) else [])
    offsets = clock_offsets_of(router_events)
    streams = [{"name": "router", "pid": 0, "events": router_events,
                "shift_s": 0.0}]
    for i, rep_dir in enumerate(replica_obs_dirs):
        name = os.path.basename(os.path.normpath(rep_dir))
        path = os.path.join(rep_dir, "events.jsonl")
        events = load_span_events(path) if os.path.exists(path) else []
        streams.append({
            "name": name, "pid": i + 1, "events": events,
            # offset = replica_clock - router_clock, so subtracting it
            # maps the replica's timestamps onto the router timeline
            "shift_s": -offsets.get(name, 0.0),
        })
    return streams


def assemble_fleet_traces(fleet_obs_dir: str,
                          replica_obs_dirs: Optional[List[str]] = None
                          ) -> Dict[str, dict]:
    """Cross-process per-request traces on the router clock (see
    ``obs.trace_export.assemble_request_traces``)."""
    return trace_export.assemble_request_traces(
        collect_streams(fleet_obs_dir, replica_obs_dirs))


def trace_summary(traces: Dict[str, dict]) -> Dict[str, int]:
    """The drill's contiguity verdict over assembled traces:

    - ``assembled`` — traces with any stage/summary event;
    - ``completed`` — traces whose terminal outcome is ``complete``;
    - ``cross_process`` — completed traces whose waterfall spans BOTH a
      router pid and a replica pid and shows the replica-side serving
      stages (prefill/first_token) — the router accept → replica decode
      → completion contiguity the drill asserts for EVERY completed
      request;
    - ``redriven_cross_process`` — cross-process traces that carry a
      redrive stage or a second dispatch attempt (both attempts
      visible);
    - ``torn`` — traces with stage events but no terminal summary (a
      request that died with its replica AND never completed anywhere).
    """
    out = {"assembled": len(traces), "completed": 0, "cross_process": 0,
           "redriven_cross_process": 0, "torn": 0}
    for t in traces.values():
        names = {s.get("stage") for s in t["stages"]}
        if t.get("torn"):
            out["torn"] += 1
        if t.get("outcome") != "complete":
            continue
        out["completed"] += 1
        cross = (len(t["pids"]) >= 2 and 0 in t["pids"]
                 and ("prefill" in names or "first_token" in names))
        if cross:
            out["cross_process"] += 1
            if t.get("redrive") or t.get("attempts", 0) >= 2:
                out["redriven_cross_process"] += 1
    return out


def slowest_exemplars(traces: Dict[str, dict], k: int = 8) -> List[dict]:
    """The K slowest completed traces as compact waterfall records for
    the ledger / ``obs report`` (stage name + start offset + duration,
    ms, relative to the trace's first stage)."""
    done = [(tid, t) for tid, t in traces.items()
            if t.get("outcome") == "complete" and t["stages"]]
    done.sort(key=lambda kv: -(kv[1].get("e2e_s") or 0.0))
    out = []
    for tid, t in done[:k]:
        t0 = t["stages"][0]["ts"]
        out.append({
            "trace": tid,
            "e2e_ms": (round(1e3 * t["e2e_s"], 3)
                       if t.get("e2e_s") is not None else None),
            "ttft_ms": (round(1e3 * t["ttft_s"], 3)
                        if t.get("ttft_s") is not None else None),
            "attempts": t.get("attempts", 0),
            "redrive": bool(t.get("redrive")),
            "stages": [{
                "stage": s.get("stage"),
                "at_ms": round(1e3 * (s["ts"] - t0), 3),
                "dur_ms": round(1e3 * float(s.get("dur_s") or 0.0), 3),
                "pid": s.get("pid"),
            } for s in t["stages"]],
        })
    return out


def write_fleet_trace(fleet_obs_dir: str,
                      replica_obs_dirs: Optional[List[str]] = None,
                      out_path: Optional[str] = None) -> str:
    """The ONE merged ``trace.json``: router + replica span flames on
    distinct pids plus the per-request waterfall tracks.  Overwrites the
    fleet session's own (router-only) export — call after
    ``obs.shutdown()``.  Returns the written path."""
    streams = collect_streams(fleet_obs_dir, replica_obs_dirs)
    traces = trace_export.assemble_request_traces(streams)
    if out_path is None:
        out_path = os.path.join(fleet_obs_dir,
                                trace_export.TRACE_FILENAME)
    return trace_export.write_merged_trace(streams, out_path,
                                           traces=traces)


def merge_timeseries(fleet_obs_dir: str,
                     replica_obs_dirs: Optional[List[str]] = None
                     ) -> Dict[str, int]:
    """Merge every fleet process's windowed metric time-series
    (``metrics_ts.jsonl`` — obs.timeseries) into ONE
    ``metrics_ts_fleet.jsonl`` on the **router clock**: each window
    record is stamped with its process (``proc``/``pid``, matching the
    trace-assembly placement — router pid 0, replica<i> pid i+1) and
    its ``ts`` shifted by that replica's estimated clock offset (same
    ``clock_offset`` machinery :func:`collect_streams` uses).  Records
    are emitted in shifted-time order, so the merged stream reads as
    one fleet-wide timeline — per-replica occupancy/queue-depth history
    next to the router's own scraped gauges.

    Returns ``{"streams": ..., "windows": ...}``.  A kill -9'd replica
    contributes its readable prefix (the recorder flushes per line)."""
    from torchpruner_tpu.obs.timeseries import (
        TS_FLEET_FILENAME,
        load_series,
    )
    from torchpruner_tpu.utils.profiling import load_span_events

    if replica_obs_dirs is None:
        replica_obs_dirs = replica_obs_dirs_of(fleet_obs_dir)
    router_path = os.path.join(fleet_obs_dir, "events.jsonl")
    offsets = clock_offsets_of(
        load_span_events(router_path)
        if os.path.exists(router_path) else [])
    sources = [("router", 0, fleet_obs_dir, 0.0)]
    for i, rep_dir in enumerate(replica_obs_dirs):
        name = os.path.basename(os.path.normpath(rep_dir))
        # offset = replica_clock - router_clock → subtract to re-home
        sources.append((name, i + 1, rep_dir, -offsets.get(name, 0.0)))
    merged: List[dict] = []
    streams = 0
    for name, pid, run_dir, shift_s in sources:
        _, windows = load_series(run_dir)
        if not windows:
            continue
        streams += 1
        for w in windows:
            rec = dict(w)
            rec["proc"] = name
            rec["pid"] = pid
            rec["ts"] = round(float(w.get("ts") or 0.0) + shift_s, 6)
            if shift_s:
                rec["shift_s"] = round(shift_s, 6)
            merged.append(rec)
    merged.sort(key=lambda r: r["ts"])
    out_path = os.path.join(fleet_obs_dir, TS_FLEET_FILENAME)
    # a derived, regenerable artifact (not a durable log): plain
    # write-and-close, re-run to rebuild
    with open(out_path, "w") as f:
        for rec in merged:
            f.write(json.dumps(rec) + "\n")
    return {"streams": streams, "windows": len(merged)}


def assemble_fleet_incidents(fleet_obs_dir: str,
                             lookback_s: Optional[float] = None
                             ) -> Dict[str, object]:
    """Offline fleet-merged incident assembly **on the router clock**
    (the ``obs incident DIR`` reconstruction path for fleet dirs): the
    fleet ledger's burn alerts (re-homed with their original
    ``burn_ts``) plus per-process offline anomaly detection over
    ``metrics_ts_fleet.jsonl`` become triggers; suspects come from the
    fleet ledger (chaos injections, scale decisions, swaps); gauge
    deltas from the router process's windows (the scrape history —
    ``fleet_replica_*`` gauges); exemplars from the ledgered reqtrace
    record.  Same coalescing as the online correlator, so a kill -9'd
    drill reconstructs the same postmortem.  Returns
    ``{"incidents", "anomalies", "burns", "records"}``."""
    from torchpruner_tpu.obs import incident
    from torchpruner_tpu.obs.anomaly import detect_anomalies
    from torchpruner_tpu.obs.ledger import LEDGER_FILENAME, load_ledger
    from torchpruner_tpu.obs.timeseries import (
        TS_FLEET_FILENAME,
        load_series,
    )

    path = os.path.join(fleet_obs_dir, LEDGER_FILENAME)
    records = load_ledger(path) if os.path.exists(path) else []
    try:
        anomalies = detect_anomalies(fleet_obs_dir)
    except Exception:
        anomalies = []
    try:
        _, windows = load_series(
            os.path.join(fleet_obs_dir, TS_FLEET_FILENAME))
    except Exception:
        windows = []
    router_windows = [w for w in windows
                      if (w.get("proc") or "router") == "router"]
    gauge_history = [(w.get("ts") or 0.0, w["gauges"])
                     for w in router_windows if w.get("gauges")]
    exemplars = None
    for rec in reversed(records):
        if rec.get("event") == "reqtrace" and rec.get("exemplars"):
            exemplars = rec["exemplars"]
            break
    tenants: List[str] = []
    if gauge_history:
        try:
            tenants = incident.affected_tenants(gauge_history[-1][1])
        except Exception:
            tenants = []
    burns = [r for r in records
             if r.get("event") == "serve" and r.get("kind") == "slo_burn"]
    incidents = incident.correlate(
        incident.triggers_of(records, anomalies), records,
        lookback_s=lookback_s, gauge_history=gauge_history,
        exemplars=exemplars, tenants=tenants or None)
    return {"incidents": incidents, "anomalies": anomalies,
            "burns": burns, "records": records}


def replica_summary_line(log_path: str) -> Optional[dict]:
    """The last JSON line a serve front end printed (its run summary),
    scraped from the replica's captured output — best-effort."""
    try:
        with open(log_path, "rb") as f:
            lines = f.read().decode(errors="replace").splitlines()
    except OSError:
        return None
    for line in reversed(lines):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None

"""The fleet router: health-checked least-loaded dispatch + failover.

One router process fronts N serve replicas and makes replica death a
non-event:

- **Health view** — every ``health_every_s`` the router probes each
  replica's ``/healthz`` (liveness split from readiness: ``draining`` /
  ``staging_swap`` / ``slo_breach`` answer 503) and scrapes the live
  ``kv_page_occupancy`` / ``slot_utilization`` / ``queue_depth`` gauges
  from ``/stats``.  A live→dead transition is a FAILOVER: the dead
  replica's journaled in-flight records are hedge-re-dispatched to
  survivors immediately (``fleet_failover_total`` /
  ``fleet_redrive_total``) — the worker still blocked on the corpse's
  socket discovers the death itself and its late result, if any, is
  dropped idempotently.
- **Dispatch** — pending plane records go to the least-loaded READY
  replica (scraped occupancy + queue depth + the router's own in-flight
  count); each record's attempt loop is
  ``resilience.retry.with_retries``: bounded attempts, per-attempt
  timeout clamped by the record's deadline budget, deterministic-jitter
  exponential backoff, pinned exhaustion-vs-deadline ordering.  When no
  replica is ready the attempt fails retryably — survivor recovery and
  backoff, not a crash.
- **Degraded-mode admission** — :meth:`FleetRouter.submit` sheds by
  policy instead of collapsing: a bounded pending queue
  (``queue_bound``), tightened to ``degraded_queue_factor`` of itself
  while the fleet is degraded (a majority of live replicas in
  ``slo_breach``, or fewer ready replicas than ``min_ready``), and a
  loud 503-shaped shed (``fleet_shed_*_total`` + Retry-After hint)
  when the bound is hit or nothing is live.
- **Fleet upgrade as a loop** — :meth:`rolling_swap` stages PR 6's
  background checkpoint hot-swap on one replica at a time, waiting for
  each swap to land (readiness flips through ``staging_swap`` and the
  router routes around it) before touching the next.
- **Prefix affinity** (Serve v2) — requests carrying a ``session_id``,
  or whose prompt starts with a previously-seen leading chunk, PREFER
  the replica that served that key last (:class:`PrefixAffinity`):
  landing them together compounds that replica's prefix-cache hits
  (``--prefix-pages``), turning the per-replica radix cache into a
  fleet-wide one without any cross-replica KV traffic.  Affinity is a
  ROUTING HINT, never a correctness constraint: an unusable preferred
  replica falls back to least-loaded, and a failover forgets every key
  pointing at the corpse.  ``fleet_affinity_*`` counters/gauges feed
  the PR 17 time-series plane.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from torchpruner_tpu import obs
from torchpruner_tpu.obs import reqtrace
from torchpruner_tpu.fleet.plane import PlaneRecord, RequestPlane
from torchpruner_tpu.fleet.replica import (
    ReplicaBusy,
    ReplicaClient,
    ReplicaError,
)
from torchpruner_tpu.resilience.retry import (
    Deadline,
    DeadlineExceeded,
    RetryPolicy,
    with_retries,
)


@dataclass(frozen=True)
class RouterPolicy:
    """Every budget/bound in one place (CLI-overridable)."""

    #: pending-queue bound; submissions past it shed (0 = unbounded)
    queue_bound: int = 64
    #: bound multiplier while the fleet is degraded (SLO-breach
    #: majority / not enough ready replicas) — admission tightening
    degraded_queue_factor: float = 0.25
    #: live replicas in slo_breach at/above this fraction = degraded
    degraded_breach_fraction: float = 0.5
    #: fewer READY replicas than this = degraded
    min_ready: int = 1
    #: dispatch attempts per record (first try included) — generous:
    #: a capacity crunch ("no usable replica") consumes attempts too,
    #: and an accepted record failed on attempts is accepted-request
    #: loss, the thing the drill exists to forbid
    max_attempts: int = 10
    #: per-attempt transport timeout (clamped by the record deadline)
    attempt_timeout_s: float = 90.0
    #: deadline budget stamped on records submitted without one
    default_deadline_s: float = 300.0
    base_backoff_s: float = 0.05
    max_backoff_s: float = 2.0
    #: deterministic-jitter seed (resilience.retry)
    seed: int = 0
    health_every_s: float = 0.5
    health_timeout_s: float = 2.0
    #: concurrent in-flight dispatches per replica (≈ slots + a margin
    #: that keeps the replica's bounded queue warm without flooding it)
    max_inflight_per_replica: int = 4
    #: rolling_swap's swap-counter poll cadence (was a hardcoded sleep)
    swap_poll_s: float = 0.25
    #: run_until_drained's default tick sleep (drills override per call)
    drain_poll_s: float = 0.02
    #: leading prompt tokens hashed into the prefix-affinity key (a
    #: prompt shorter than this registers no prefix key); 0 disables
    #: prefix-affinity routing entirely (session keys included)
    affinity_prefix_tokens: int = 16
    #: affinity-registry bound (LRU past it) — a long-running endpoint
    #: must not grow router memory with lifetime session count
    affinity_max_keys: int = 4096

    def retry_policy(self) -> RetryPolicy:
        return RetryPolicy(
            tries=self.max_attempts, base_delay_s=self.base_backoff_s,
            max_delay_s=self.max_backoff_s, seed=self.seed)


class PrefixAffinity:
    """Affinity-key → replica-name registry (see module docstring).

    Two key kinds per request, strongest first: ``("s", session_id)``
    (caller-asserted session) and ``("p", hash(leading tokens))`` (the
    first ``prefix_tokens`` prompt ids — the same leading chunk the
    replica's radix trie would match).  :meth:`note` registers both at
    completion; :meth:`preferred` answers the longest-signal match;
    :meth:`forget` drops every key pointing at a dead replica.  LRU-
    bounded at ``max_keys``.  Not thread-safe — callers hold the
    router's lock."""

    def __init__(self, prefix_tokens: int, max_keys: int = 4096):
        self.prefix_tokens = int(prefix_tokens)
        self.max_keys = int(max_keys)
        from collections import OrderedDict
        self._map: "OrderedDict[tuple, str]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._map)

    def keys_of(self, payload: dict) -> List[tuple]:
        """The request's affinity keys, strongest first."""
        if self.prefix_tokens <= 0:
            return []
        keys: List[tuple] = []
        sid = payload.get("session_id")
        if sid:
            keys.append(("s", str(sid)))
        ids = payload.get("prompt_ids") or []
        if len(ids) >= self.prefix_tokens:
            keys.append(
                ("p", hash(tuple(ids[:self.prefix_tokens]))))
        return keys

    def preferred(self, payload: dict) -> Optional[str]:
        for key in self.keys_of(payload):
            name = self._map.get(key)
            if name is not None:
                self._map.move_to_end(key)
                return name
        return None

    def note(self, payload: dict, replica: str) -> None:
        for key in self.keys_of(payload):
            self._map[key] = replica
            self._map.move_to_end(key)
        while len(self._map) > self.max_keys:
            self._map.popitem(last=False)

    def forget(self, replica: str) -> int:
        """Drop every key routed at ``replica`` (failover); returns how
        many were dropped — stale affinity to a corpse would fight the
        exclude/least-loaded fallback on every subsequent request."""
        dead = [k for k, v in self._map.items() if v == replica]
        for k in dead:
            del self._map[k]
        return len(dead)


@dataclass
class ReplicaView:
    """The router's last-probed view of one replica."""

    client: ReplicaClient
    live: bool = False
    ready: bool = False
    state: str = "unknown"
    occupancy: float = 0.0
    slot_utilization: float = 0.0
    queue_depth: int = 0
    swaps: int = 0
    probed_at: float = 0.0
    #: estimated clock offset (replica wall clock − router wall clock,
    #: seconds) from the health probe's request/response timestamps —
    #: the alignment the cross-process trace assembly uses.  The kept
    #: sample is the lowest-RTT one seen recently (NTP-style: a slow
    #: probe bounds the offset loosely)
    clock_offset: Optional[float] = None
    offset_rtt: Optional[float] = None
    offset_at: float = 0.0
    offset_emitted: Optional[float] = None
    #: set once the death was failed over (so one death = one failover)
    failover_done: bool = False
    dispatched_total: int = 0
    inflight: int = 0
    #: scale-down victim: never picked for NEW dispatches, but still
    #: probed/live while its in-flight work drains (the supervisor's
    #: drain-then-remove contract)
    retiring: bool = False
    extra: dict = field(default_factory=dict)


class FleetRouter:
    """See module docstring.  Thread model: front ends call
    :meth:`submit` from any thread; :meth:`tick` runs on the owner's
    loop (drill driver or the HTTP server's pump thread); dispatch
    attempts run on an internal executor, one worker per in-flight
    record."""

    def __init__(self, plane: RequestPlane,
                 replicas: List[ReplicaClient],
                 policy: RouterPolicy = RouterPolicy()):
        self.plane = plane
        self.policy = policy
        self.views: Dict[str, ReplicaView] = {
            r.name: ReplicaView(client=r) for r in replicas}
        self._lock = threading.RLock()
        self._pool = ThreadPoolExecutor(
            max_workers=max(2, policy.max_inflight_per_replica
                            * len(replicas)),
            thread_name_prefix="fleet-dispatch")
        #: records checked out of the plane whose dispatch worker has
        #: not finished — the pump()'s capacity gate
        self._workers_out = 0
        self._last_health = 0.0
        self.failovers_total = 0
        self.shed_total = 0
        self.dispatched_total = 0
        self.affinity = PrefixAffinity(
            policy.affinity_prefix_tokens,
            max_keys=policy.affinity_max_keys)
        #: records that carried a usable affinity preference / of those,
        #: how many actually landed on the preferred replica
        self.affinity_preferred_total = 0
        self.affinity_hits_total = 0
        #: tenants currently load-shed at admission (the degradation
        #: ladder's first rung: the supervisor sheds the batch tier
        #: here before touching interactive traffic) — mutated under
        #: the router lock, reversible
        self.shed_tenants: set = set()
        #: supervisor-forced admission tightening (degradation-ladder
        #: rung 2): degraded() answers True while set, shrinking the
        #: effective queue bound by degraded_queue_factor
        self.force_degraded = False
        #: tenant -> live counters + recent latency samples (the
        #: per-tenant SLO breakdown the drill summary / obs report
        #: render); guarded by the router lock
        self._tenants: Dict[str, dict] = {}
        self._closed = False

    # -- per-tenant accounting ----------------------------------------------

    def _tenant_entry_locked(self, tenant: str) -> dict:
        ent = self._tenants.get(tenant)
        if ent is None:
            ent = {"accepted": 0, "completed": 0, "shed": 0,
                   "deadline_exceeded": 0,
                   "ttft_s": deque(maxlen=4096),
                   "e2e_s": deque(maxlen=4096)}
            self._tenants[tenant] = ent
        return ent

    def _tenant_note(self, tenant: Optional[str], event: str,
                     ttft_s: Optional[float] = None,
                     e2e_s: Optional[float] = None) -> None:
        if not tenant:
            return
        with self._lock:
            ent = self._tenant_entry_locked(tenant)
            ent[event] = ent.get(event, 0) + 1
            if ttft_s is not None:
                ent["ttft_s"].append(float(ttft_s))
            if e2e_s is not None:
                ent["e2e_s"].append(float(e2e_s))

    def tenant_summary(self) -> Dict[str, dict]:
        """Per-tenant counters + latency percentiles, and the
        ``tenant_<name>_*`` gauges obs report's per-tenant SLO table is
        built from (exported here, at read-out time, so the scalars
        carry final percentiles rather than a racing snapshot)."""
        def pct(xs, q):
            if not xs:
                return None
            xs = sorted(xs)
            return xs[min(len(xs) - 1, max(0, round(q * (len(xs) - 1))))]

        with self._lock:
            tenants = {t: dict(ent, ttft_s=list(ent["ttft_s"]),
                               e2e_s=list(ent["e2e_s"]))
                       for t, ent in self._tenants.items()}
        out: Dict[str, dict] = {}
        for t, ent in sorted(tenants.items()):
            row = {"accepted": ent["accepted"],
                   "completed": ent["completed"],
                   "shed": ent["shed"],
                   "deadline_exceeded": ent["deadline_exceeded"],
                   "ttft_p50_s": pct(ent["ttft_s"], 0.50),
                   "ttft_p99_s": pct(ent["ttft_s"], 0.99),
                   "e2e_p50_s": pct(ent["e2e_s"], 0.50),
                   "e2e_p99_s": pct(ent["e2e_s"], 0.99)}
            out[t] = row
            for k in ("accepted", "completed", "shed",
                      "deadline_exceeded"):
                obs.gauge_set(f"tenant_{t}_{k}_fleet",
                              row[k],
                              help=f"router-observed {k} count for "
                                   f"this tenant")
            for k in ("ttft_p50_s", "ttft_p99_s", "e2e_p50_s",
                      "e2e_p99_s"):
                if row[k] is not None:
                    obs.gauge_set(
                        f"tenant_{t}_{k}", round(row[k], 6),
                        help="router-observed per-tenant latency "
                             "percentile (TTFT from replica results, "
                             "e2e accept -> complete)")
        return out

    # -- admission -----------------------------------------------------------

    def degraded(self) -> bool:
        """Admission tightening trigger: not enough ready replicas, or
        a majority of the live ones sitting in an SLO-breach episode
        (the rolling SLOMonitor p99s, scraped via /healthz state).
        ``force_degraded`` is the supervisor's degradation-ladder rung:
        the same tightened bound, entered deliberately."""
        with self._lock:
            if self.force_degraded:
                return True
            live = [v for v in self.views.values() if v.live]
            ready = [v for v in live if v.ready]
            if len(ready) < self.policy.min_ready:
                return True
            breached = [v for v in live if v.state == "slo_breach"]
            return bool(live) and (
                len(breached) / len(live)
                >= self.policy.degraded_breach_fraction)

    def effective_queue_bound(self) -> int:
        bound = self.policy.queue_bound
        if bound and self.degraded():
            bound = max(1, int(bound * self.policy.degraded_queue_factor))
        return bound

    def admission(self) -> dict:
        """One consolidated verdict for front ends: ``accepting`` plus
        the shed reason / Retry-After hint when not."""
        with self._lock:
            # membership is elastic now (supervisor add/remove):
            # snapshot under the lock so a resize mid-iteration can't
            # fault a submitting frontend thread
            live = [v for v in self.views.values() if v.live]
        if self._closed:
            return {"accepting": False, "reason": "closing",
                    "retry_after_s": 5, "code": 503}
        if not live:
            return {"accepting": False, "reason": "no_live_replica",
                    "retry_after_s": 5, "code": 503}
        bound = self.effective_queue_bound()
        depth = self.plane.pending_depth
        if bound and depth >= bound:
            reason = ("degraded" if bound < self.policy.queue_bound
                      else "backpressure")
            return {"accepting": False, "reason": reason,
                    "retry_after_s": max(1, depth // max(1, len(live))),
                    "code": 429}
        return {"accepting": True, "reason": "", "retry_after_s": 0,
                "code": 200}

    def submit(self, payload: dict,
               deadline_s: Optional[float] = None
               ) -> Optional[PlaneRecord]:
        """Admit one request into the plane, or shed it (``None``) by
        the current policy — bounded queue, tighter while degraded,
        immediate when nothing is live."""
        if self._last_health == 0.0:
            # first contact: an unprobed fleet must not read as dead
            self.check_health(force=True)
        tenant = payload.get("tenant")
        verdict = self.admission()
        if verdict["accepting"] and tenant is not None:
            with self._lock:
                tier_shed = tenant in self.shed_tenants
            if tier_shed:
                # degradation-ladder rung 1: this tenant's tier is
                # load-shed while the supervisor buys capacity back —
                # 503 + Retry-After, reversible, interactive untouched
                verdict = {"accepting": False, "reason": "tier",
                           "retry_after_s": 2, "code": 503}
        if not verdict["accepting"]:
            self.shed_total += 1
            self.plane.note_shed()
            obs.inc("fleet_shed_total",
                    help="requests shed at fleet admission (per-reason "
                         "twins: fleet_shed_<reason>_total)")
            obs.inc(f"fleet_shed_{verdict['reason']}_total",
                    help=f"fleet admission sheds ({verdict['reason']})")
            self._tenant_note(tenant, "shed")
            # a shed request never enters the plane; the refusal always
            # counts into the aggregate stage counters, and its trace
            # events reach the stream eagerly (drills) or 1-in-N by the
            # sampling hash (a sustained-overload endpoint must not
            # write a line per shed)
            tid = reqtrace.mint_trace_id("shed")
            reqtrace.stage(tid, "shed", reason=verdict["reason"])
            reqtrace.finish(tid, outcome="shed",
                            reason=verdict["reason"])
            return None
        rec = self.plane.accept(
            payload, deadline_s if deadline_s is not None
            else self.policy.default_deadline_s)
        self._tenant_note(tenant, "accepted")
        return rec

    # -- health --------------------------------------------------------------

    def check_health(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_health \
                < self.policy.health_every_s:
            return
        self._last_health = now
        for view in list(self.views.values()):
            t0 = time.perf_counter()
            h = view.client.healthz(timeout=self.policy.health_timeout_s)
            was_live = view.live
            view.live, view.ready = h["live"], h["ready"]
            view.state = h["state"]
            view.probed_at = now
            self._note_clock_offset(view, h)
            if view.live:
                view.failover_done = False
                try:
                    s = view.client.stats(
                        timeout=self.policy.health_timeout_s)
                    view.occupancy = float(
                        s.get("kv_page_occupancy", 0.0))
                    view.slot_utilization = float(
                        s.get("slot_utilization", 0.0))
                    view.queue_depth = int(s.get("queue_depth", 0))
                    view.swaps = int(s.get("swaps",
                                           s.get("hot_swaps", 0)) or 0)
                    view.extra = {k: s.get(k) for k in (
                        "slo", "decode_steps", "gen_tokens")}
                except ReplicaError:
                    pass
            elif was_live or not view.failover_done:
                self._failover(view)
            # scrape history: RTT into a histogram, the scraped view
            # into per-replica gauges — which the router's own
            # time-series recorder snapshots every window, giving the
            # fleet a per-replica occupancy/queue-depth HISTORY (the
            # autoscaler's sensor input; obs.timeseries)
            self._record_scrape(view, time.perf_counter() - t0)
        with self._lock:
            live = sum(v.live for v in self.views.values())
            ready = sum(v.ready for v in self.views.values())
        obs.gauge_set("fleet_replicas_live", live,
                      help="replicas answering their health probe")
        obs.gauge_set("fleet_replicas_ready", ready,
                      help="replicas in the ready routing set")
        obs.gauge_set("fleet_pending_depth", self.plane.pending_depth,
                      help="plane records awaiting dispatch")
        # age, not just depth: one starved record aging toward its
        # deadline is invisible to a depth gauge — this is the
        # autoscaling supervisor's primary scale-up signal
        obs.gauge_set("fleet_queue_age_seconds",
                      round(self.plane.oldest_pending_age_s(), 6),
                      help="age of the OLDEST plane record awaiting "
                           "dispatch (0 when none pending)")

    #: replica state → the numeric code the per-replica state gauge
    #: carries (a time-series sample must be a scalar)
    STATE_CODES = {"ready": 0, "draining": 1, "staging_swap": 2,
                   "slo_breach": 3}

    def _record_scrape(self, view: ReplicaView, rtt_s: float) -> None:
        """One health-scrape's telemetry: RTT observation + the
        scraped per-replica gauges (`fleet_replica_<name>_*`)."""
        obs.observe("fleet_scrape_seconds", rtt_s,
                    help="router health-scrape round trip "
                         "(healthz + stats) per replica")
        name = "".join(c if c.isalnum() else "_"
                       for c in view.client.name)
        prefix = f"fleet_replica_{name}"
        code = (self.STATE_CODES.get(view.state, -1)
                if view.live else -1)
        obs.gauge_set(f"{prefix}_state_code", code,
                      help="scraped replica state (0 ready, 1 draining,"
                           " 2 staging_swap, 3 slo_breach, -1 dead)")
        obs.gauge_set(f"{prefix}_scrape_rtt_s", round(rtt_s, 6),
                      help="last health-scrape RTT for this replica")
        if view.live:
            obs.gauge_set(f"{prefix}_occupancy", view.occupancy,
                          help="scraped KV-page occupancy")
            obs.gauge_set(f"{prefix}_queue_depth", view.queue_depth,
                          help="scraped scheduler queue depth")

    def _note_clock_offset(self, view: ReplicaView, h: dict) -> None:
        """Keep the best (lowest-RTT) clock-offset sample the health
        probe produced and emit it into the event stream (rate-limited
        to real changes) — the per-replica alignment
        ``fleet.report.collect_streams`` shifts that replica's
        ``events.jsonl`` by when assembling the cross-process trace."""
        off, rtt = h.get("clock_offset_s"), h.get("rtt_s")
        if off is None:
            return
        rtt = float(rtt or 0.0)
        now = time.monotonic()
        # NTP-style: keep the lowest-RTT sample (a slower probe bounds
        # the offset more loosely) — offset and rtt travel as one pair.
        # A stale best sample (>60 s) is replaced regardless, so a slow
        # clock drift is still tracked.
        if view.offset_rtt is not None and rtt > view.offset_rtt \
                and now - view.offset_at < 60.0:
            return
        view.clock_offset = float(off)
        view.offset_rtt = rtt
        view.offset_at = now
        if view.offset_emitted is None \
                or abs(view.clock_offset - view.offset_emitted) > 5e-4:
            view.offset_emitted = view.clock_offset
            obs.emit_event({
                "event": "clock_offset", "ts": time.time(),
                "replica": view.client.name,
                "offset_s": round(view.clock_offset, 6),
                "rtt_s": round(rtt, 6),
            })

    def _failover(self, view: ReplicaView) -> None:
        """A replica left the live set: count the failover once and
        hedge-re-dispatch its journaled in-flight records to survivors
        (their original workers are still blocked on the corpse's
        socket — first completion wins, duplicates drop)."""
        with self._lock:
            # dispatch workers probe health concurrently with the tick
            # loop: exactly ONE of them owns this death
            if view.failover_done:
                return
            view.failover_done = True
            self.failovers_total += 1
        obs.inc("fleet_failover_total",
                help="replica deaths observed by the health monitor")
        with self._lock:
            dropped = self.affinity.forget(view.client.name)
        if dropped:
            obs.inc("fleet_affinity_forgotten_total", n=dropped,
                    help="affinity keys dropped because their replica "
                         "left the live set")
        rids = self.plane.assigned_to(view.client.name)
        print(f"[fleet] replica {view.client.name} is gone "
              f"({len(rids)} in-flight record(s) redriven)",
              file=sys.stderr, flush=True)
        for rid in rids:
            if self.plane.release(rid, redrive=True):
                self._spawn_dispatch()

    # -- dispatch ------------------------------------------------------------

    # -- elastic membership (the autoscaling supervisor's verbs) -------------

    def add_replica(self, client: ReplicaClient) -> ReplicaView:
        """Join a freshly-launched replica to the routing set (scale
        up).  The view starts unprobed; the next health tick flips it
        live/ready and it begins taking dispatches."""
        with self._lock:
            if client.name in self.views:
                raise ValueError(f"replica {client.name!r} already "
                                 f"routed")
            view = ReplicaView(client=client)
            self.views[client.name] = view
            # grow the dispatch pool ceiling with membership — the
            # executor spawns workers lazily, so raising the bound here
            # is safe (shrinking happens naturally via idle workers)
            self._pool._max_workers = max(
                self._pool._max_workers,
                self.policy.max_inflight_per_replica * len(self.views))
        obs.inc("fleet_replicas_added_total",
                help="replicas joined to the routing set (scale-up)")
        return view

    def begin_retire(self, name: str) -> bool:
        """Mark a replica as a scale-down victim: it stops receiving
        NEW dispatches immediately but keeps its in-flight work (and
        its health probes).  Reversible via :meth:`cancel_retire`."""
        with self._lock:
            view = self.views.get(name)
            if view is None:
                return False
            view.retiring = True
        return True

    def cancel_retire(self, name: str) -> bool:
        with self._lock:
            view = self.views.get(name)
            if view is None:
                return False
            view.retiring = False
        return True

    def retired_idle(self, name: str) -> bool:
        """True when a retiring replica holds no router in-flight work
        AND no plane record is assigned to it — the drain-then-remove
        gate (accepted requests are never lost to a scale-down)."""
        with self._lock:
            view = self.views.get(name)
            if view is None:
                return True
            if not view.retiring or view.inflight > 0:
                return False
        return not self.plane.assigned_to(name)

    def remove_replica(self, name: str) -> bool:
        """Drop a drained, retiring replica from the routing set.
        Refuses (returns False) while work is still assigned — callers
        must pass the :meth:`retired_idle` gate first."""
        if not self.retired_idle(name):
            return False
        with self._lock:
            view = self.views.pop(name, None)
            if view is None:
                return False
            n = self.affinity.forget(name)
        if n:
            obs.inc("fleet_affinity_forgotten_total", n=n,
                    help="affinity keys dropped because their replica "
                         "left the fleet")
        obs.inc("fleet_replicas_removed_total",
                help="replicas removed from the routing set after a "
                     "drain (scale-down)")
        return True

    def _pick(self, exclude: Optional[str] = None,
              prefer: Optional[str] = None) -> Optional[ReplicaView]:
        """Least-loaded routing over the scraped gauges: READY replicas
        first (excluding the just-failed one when another exists), by
        (router in-flight fraction + scraped occupancy + queue depth,
        with a tiny dispatched-count bias that round-robins exact
        ties); degraded-but-live replicas (slo_breach / staging_swap)
        are the fallback so a fully-degraded fleet still serves — only
        draining and dead replicas are never picked.  ``prefer`` names
        the prefix-affinity replica: taken when usable-and-ready (its
        warm prefix cache beats a small load delta), otherwise the
        least-loaded fallback — a hint, never a constraint.  The
        winner's in-flight slot is RESERVED under the lock (the caller
        must release it), so concurrent picks see each other's load."""
        with self._lock:
            cap = self.policy.max_inflight_per_replica

            def load(v: ReplicaView) -> float:
                return (v.inflight / max(1, cap) + v.occupancy
                        + v.slot_utilization + 0.25 * v.queue_depth
                        + 1e-3 * v.dispatched_total)

            def usable(v: ReplicaView, ready_only: bool) -> bool:
                if not v.live or v.state == "draining" or v.retiring:
                    return False
                if v.inflight >= cap:
                    return False
                return v.ready if ready_only else True

            if prefer is not None and prefer != exclude:
                v = self.views.get(prefer)
                if v is not None and usable(v, ready_only=True):
                    v.inflight += 1
                    v.dispatched_total += 1
                    return v

            for ready_only in (True, False):
                pool = [v for v in self.views.values()
                        if usable(v, ready_only)
                        and v.client.name != exclude]
                if not pool and exclude is not None:
                    pool = [v for v in self.views.values()
                            if usable(v, ready_only)]
                if pool:
                    view = min(pool, key=load)
                    view.inflight += 1
                    view.dispatched_total += 1
                    return view
            return None

    def pump(self) -> int:
        """Move pending plane records onto dispatch workers; returns
        how many were started.  Checkout is CAPACITY-GATED: a record
        leaves the plane only while some non-retiring live replica has
        a free in-flight slot, so saturation backs up in the plane's
        FIFO — where queue age (`oldest_pending_age_s`, the autoscale
        signal), the queue-bound backpressure and the redrive machinery
        all live — instead of hiding in the dispatch pool's internal
        queue.  Two escape valves keep pending work terminal anyway:
        an EXPIRED record is checked out regardless (its worker fails
        it loudly at the deadline), and a worker that loses the
        capacity race still waits deadline-bounded inside dispatch."""
        n = 0
        while self._spawn_dispatch():
            n += 1
        return n

    def _dispatch_capacity(self) -> bool:
        """Could the fleet absorb one more dispatch worker right now?
        Gated on OUTSTANDING WORKERS (not per-view ``inflight``, which
        a worker only bumps once it wins a ``_pick`` — gating on it
        would let one pump() drain the whole backlog into the pool
        during that window)."""
        with self._lock:
            cap = self.policy.max_inflight_per_replica
            usable = sum(1 for v in self.views.values()
                         if v.live and not v.retiring
                         and v.state != "draining")
            return self._workers_out < cap * usable

    def _spawn_dispatch(self) -> bool:
        rec = self.plane.checkout() if self._dispatch_capacity() \
            else self.plane.checkout_expired()
        if rec is None:
            return False
        with self._lock:
            self._workers_out += 1
        self.dispatched_total += 1
        obs.inc("fleet_dispatch_total",
                help="plane records handed to a dispatch worker")
        self._pool.submit(self._dispatch_entry, rec)
        return True

    def _dispatch_entry(self, rec: PlaneRecord) -> None:
        try:
            self._dispatch(rec)
        finally:
            with self._lock:
                self._workers_out -= 1

    def _dispatch(self, rec: PlaneRecord) -> None:
        deadline = Deadline.after(rec.remaining_s())
        last_failed: Optional[str] = None
        # affinity preference resolved ONCE per record (counted once,
        # however many attempts follow); a retry excludes the failed
        # replica, which _pick already ranks above the preference
        with self._lock:
            prefer = self.affinity.preferred(rec.payload)
        if prefer is not None:
            with self._lock:
                self.affinity_preferred_total += 1
            obs.inc("fleet_affinity_preferred_total",
                    help="dispatches that carried a session/prefix "
                         "affinity preference")
        hit_counted = [False]

        def attempt(timeout_s: Optional[float]):
            nonlocal last_failed
            # capacity/availability waits ride the DEADLINE, not the
            # attempt budget: attempts are for transport failures, so a
            # saturated-but-healthy fleet queues work instead of
            # burning retries into a spurious loss
            t_wait = time.perf_counter()
            swap_stall = False
            view = self._pick(exclude=last_failed, prefer=prefer)
            while view is None:
                with self._lock:
                    staging = any(v.live and v.state == "staging_swap"
                                  for v in self.views.values())
                if staging:
                    # the capacity crunch is (at least partly) a hot-
                    # swap taking replicas out of the routing set
                    swap_stall = True
                if deadline.expired:
                    raise DeadlineExceeded(
                        f"{rec.rid}: no usable replica before the "
                        f"deadline ({deadline.budget_s:.1f}s)")
                time.sleep(min(0.05, max(0.001,
                                         self.policy.health_every_s)))
                self.check_health()
                view = self._pick(exclude=last_failed, prefer=prefer)
            name = view.client.name
            if prefer is not None and name == prefer \
                    and not hit_counted[0]:
                # once per RECORD, like the preferred counter — a
                # failed-then-retried landing must not double-count
                hit_counted[0] = True
                with self._lock:
                    self.affinity_hits_total += 1
                obs.inc("fleet_affinity_hits_total",
                        help="preferred dispatches that landed on "
                             "their affinity replica")
            attempt_no = rec.attempts + 1
            wait_s = time.perf_counter() - t_wait
            # the latency cost of WAITING for a usable replica — the
            # invisible half of a retried dispatch (the retry counter
            # alone says nothing about time spent)
            obs.observe("fleet_dispatch_wait_seconds", wait_s,
                        help="per-attempt wait for a usable replica "
                             "plus retry backoff sleeps (dispatch "
                             "latency cost, not counted in transport)")
            reqtrace.stage(rec.trace_id,
                           "swap_stall" if swap_stall
                           else "dispatch_wait",
                           dur_s=wait_s, rid=rec.rid,
                           attempt=attempt_no, replica=name,
                           kind="capacity")
            self.plane.assign(rec.rid, name)
            try:
                # trace propagation: the replica parses trace_id out of
                # the wire payload and joins its serving stages onto
                # this request's waterfall (the journal keeps the
                # ORIGINAL payload — redrive/verify replay unchanged)
                payload = rec.payload
                if rec.trace_id:
                    payload = {**payload, "trace_id": rec.trace_id}
                out = view.client.generate(payload, timeout=timeout_s)
            except ReplicaError:
                last_failed = name
                # probe NOW so a death is seen (and its other records
                # hedge) before the backoff sleep finishes
                self.check_health(force=True)
                raise
            finally:
                with self._lock:
                    view.inflight -= 1  # release the _pick reservation
            return name, out

        policy = self.policy.retry_policy()

        def on_retry(attempt_no: int, exc: BaseException) -> None:
            # the backoff sleep with_retries is ABOUT to take (same
            # deterministic-jitter formula) — the other invisible
            # latency cost of a retried dispatch.  with_retries raises
            # WITHOUT sleeping when the backoff would cross the
            # deadline; don't record a phantom wait on that path.
            delay = policy.delay(attempt_no)
            if delay >= deadline.remaining():
                return
            obs.observe("fleet_dispatch_wait_seconds", delay,
                        help="per-attempt wait for a usable replica "
                             "plus retry backoff sleeps (dispatch "
                             "latency cost, not counted in transport)")
            reqtrace.stage(rec.trace_id, "dispatch_wait", dur_s=delay,
                           t_start=time.time(), rid=rec.rid,
                           attempt=attempt_no, kind="backoff",
                           error=type(exc).__name__)

        try:
            name, out = with_retries(
                attempt, policy=policy,
                deadline=deadline,
                attempt_timeout_s=self.policy.attempt_timeout_s,
                retry_on=(ReplicaError,), label="fleet_dispatch",
                on_retry=on_retry)
        except DeadlineExceeded as e:
            obs.inc("fleet_deadline_exceeded_total",
                    help="records failed by deadline expiry")
            tenant = rec.payload.get("tenant")
            if tenant:
                obs.inc(f"tenant_{tenant}_deadline_exceeded_total",
                        help="this tenant's records failed by deadline "
                             "expiry")
            self._tenant_note(tenant, "deadline_exceeded")
            self.plane.fail(rec.rid, f"deadline: {e}")
            return
        except ReplicaError as e:
            self.plane.fail(rec.rid, f"attempts exhausted: {e}")
            return
        except Exception as e:  # noqa: BLE001 - worker must not die silent
            self.plane.fail(rec.rid, f"{type(e).__name__}: {e}")
            return
        self.plane.complete(rec.rid, out.get("tokens", []), name)
        tenant = rec.payload.get("tenant")
        if tenant:
            # the replica's result carries its measured TTFT; e2e is
            # router-observed accept -> complete — together the
            # per-tenant SLO breakdown
            self._tenant_note(
                tenant, "completed", ttft_s=out.get("ttft_s"),
                e2e_s=max(0.0, time.time() - rec.accepted_epoch_s))
        # the request's keys now point at the replica whose radix cache
        # holds its prefix — the signal the NEXT request of the session
        # / shared system prompt routes on
        with self._lock:
            self.affinity.note(rec.payload, name)
            preferred = self.affinity_preferred_total
            hits = self.affinity_hits_total
            keys = len(self.affinity)
        obs.gauge_set("fleet_affinity_hit_rate",
                      round(hits / max(1, preferred), 4),
                      help="preferred dispatches landed on their "
                           "affinity replica / dispatches with a "
                           "preference (0..1)")
        obs.gauge_set("fleet_affinity_keys", keys,
                      help="session/prefix keys in the affinity "
                           "registry (LRU-bounded)")

    # -- the loop ------------------------------------------------------------

    def tick(self) -> None:
        """One router heartbeat: health (rate-limited) + dispatch."""
        self.check_health()
        self.pump()
        # the router loop is the fleet process's clock for the windowed
        # time-series (no record_step flows here)
        obs.timeseries_tick()

    def run_until_drained(self, *, poll_s: Optional[float] = None,
                          timeout_s: Optional[float] = None,
                          stop_event: Optional[threading.Event] = None,
                          on_tick=None) -> None:
        """Drive ticks until every accepted record is terminal (the
        drill loop); ``on_tick`` is the drill's chaos hook.  ``poll_s``
        defaults to the policy's ``drain_poll_s``."""
        if poll_s is None:
            poll_s = self.policy.drain_poll_s
        deadline = (time.monotonic() + timeout_s
                    if timeout_s is not None else None)
        while True:
            self.tick()
            if on_tick is not None:
                on_tick(self)
            if self.plane.all_terminal() \
                    and self.plane.pending_depth == 0:
                return
            if stop_event is not None and stop_event.is_set():
                return
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"fleet router: records still pending after "
                    f"{timeout_s:.0f}s: {self.plane.counts()}")
            time.sleep(poll_s)

    def close(self) -> None:
        self._closed = True
        self._pool.shutdown(wait=True)

    # -- fleet upgrade -------------------------------------------------------

    def rolling_swap(self, checkpoint: str, *, wait_s: float = 600.0,
                     only: Optional[List[str]] = None) -> int:
        """Staggered checkpoint hot-swap: one replica at a time, POST
        /swap then wait for its swap counter to tick (readiness passes
        through ``staging_swap`` and the router routes around it), then
        the next — the zero-downtime fleet upgrade loop.  Returns how
        many replicas swapped.  ``only`` restricts the pass to named
        replicas (the degradation ladder's pruned-checkpoint rung swaps
        just the batch tier)."""
        swapped = 0
        with self._lock:
            views = list(self.views.values())
        for view in views:
            if not view.live:
                continue
            if only is not None and view.client.name not in only:
                continue
            c = view.client
            before = int(c.stats(timeout=5.0).get("swaps", 0) or 0)
            c.swap(checkpoint)
            obs.inc("fleet_swaps_staged_total",
                    help="rolling-upgrade swap stagings issued")
            deadline = time.monotonic() + wait_s
            while time.monotonic() < deadline:
                try:
                    if int(c.stats(timeout=5.0).get("swaps", 0) or 0) \
                            > before:
                        swapped += 1
                        break
                except ReplicaError:
                    pass
                time.sleep(self.policy.swap_poll_s)
            else:
                raise TimeoutError(
                    f"rolling swap: {c.name} did not land its swap "
                    f"inside {wait_s:.0f}s")
        return swapped

    # -- reporting -----------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            reps = {
                name: {
                    "live": v.live, "ready": v.ready, "state": v.state,
                    "occupancy": v.occupancy,
                    "slot_utilization": v.slot_utilization,
                    "queue_depth": v.queue_depth,
                    "inflight": v.inflight,
                    "dispatched_total": v.dispatched_total,
                } for name, v in self.views.items()}
        return {
            "replicas": reps,
            "plane": self.plane.counts(),
            "degraded": self.degraded(),
            "queue_bound": self.policy.queue_bound,
            "effective_queue_bound": self.effective_queue_bound(),
            "failovers_total": self.failovers_total,
            "shed_total": self.shed_total,
            "dispatched_total": self.dispatched_total,
            "affinity": {
                "preferred": self.affinity_preferred_total,
                "hits": self.affinity_hits_total,
                "hit_rate": round(
                    self.affinity_hits_total
                    / max(1, self.affinity_preferred_total), 4),
                "keys": len(self.affinity),
            },
        }


def summary_json(router: FleetRouter) -> str:
    return json.dumps(router.snapshot())

"""Replica handles: the HTTP client view and the subprocess manager.

A replica is one ``python -m torchpruner_tpu serve <preset> --http``
process.  :class:`ReplicaClient` is the router's transport — generate /
healthz / stats / metrics / swap over the single-replica front end's
endpoints, with every transport failure normalized into the
:class:`ReplicaError` family so the dispatch retry loop
(``resilience.retry.with_retries``) has ONE retryable exception
surface:

- :class:`ReplicaDown` — connection refused/reset, bad socket: the
  process is (or just became) unreachable;
- :class:`ReplicaTimeout` — the socket timed out / the front end
  answered 504: alive but not answering inside the attempt budget;
- :class:`ReplicaBusy` — 503 + Retry-After: the replica's bounded
  queue shed the request (backpressure, not death);
- :class:`ReplicaRejected` — the replica answered but refused the
  request terminally for THIS replica (draining / shed mid-wait).

:class:`ReplicaProcess` adds lifecycle: spawn with its own obs dir,
``kill -9`` / SIGSTOP ("hang") / SIGCONT for the chaos drills, and
drain (SIGTERM) + wait at shutdown.
"""

from __future__ import annotations

import http.client
import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request
from typing import List, Optional


class ReplicaError(OSError):
    """Base of every transport-level replica failure (retryable)."""


class ReplicaDown(ReplicaError):
    pass


class ReplicaTimeout(ReplicaError):
    pass


class ReplicaBusy(ReplicaError):
    def __init__(self, msg: str, retry_after_s: float = 1.0,
                 body: Optional[dict] = None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s
        self.body = body or {}


class ReplicaRejected(ReplicaError):
    pass


def free_port() -> int:
    """An OS-assigned free TCP port (bind-to-0 probe; the usual small
    race with other processes is acceptable for drills/tests)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class ReplicaClient:
    """HTTP view of one serve replica (see module docstring)."""

    def __init__(self, name: str, port: int, host: str = "127.0.0.1"):
        self.name = name
        self.host, self.port = host, int(port)
        self.base_url = f"http://{host}:{self.port}"

    # -- raw transport ------------------------------------------------------

    def _request(self, path: str, *, data: Optional[bytes] = None,
                 timeout: Optional[float] = None) -> dict:
        req = urllib.request.Request(
            self.base_url + path, data=data,
            headers={"Content-Type": "application/json"}
            if data is not None else {})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return json.load(resp)
        except urllib.error.HTTPError as e:
            if e.code == 503:
                try:
                    retry_after = float(e.headers.get("Retry-After", 1))
                except (TypeError, ValueError):
                    retry_after = 1.0
                try:
                    body = json.load(e)
                except Exception:
                    body = {}
                raise ReplicaBusy(
                    f"{self.name}{path}: 503 {body.get('error', '')}",
                    retry_after_s=retry_after, body=body) from e
            if e.code == 504:
                raise ReplicaTimeout(
                    f"{self.name}{path}: 504 request timed out") from e
            raise ReplicaRejected(
                f"{self.name}{path}: HTTP {e.code}") from e
        except urllib.error.URLError as e:
            if isinstance(e.reason, (socket.timeout, TimeoutError)):
                raise ReplicaTimeout(
                    f"{self.name}{path}: socket timeout") from e
            raise ReplicaDown(f"{self.name}{path}: {e.reason}") from e
        except http.client.HTTPException as e:
            # a kill -9 mid-response surfaces as IncompleteRead /
            # BadStatusLine — NOT an OSError; it MUST normalize into
            # the retryable family or the drill's exact failure mode
            # (death while the router reads the body) escapes redrive
            raise ReplicaDown(
                f"{self.name}{path}: torn response "
                f"({type(e).__name__}: {e})") from e
        except json.JSONDecodeError as e:
            raise ReplicaDown(
                f"{self.name}{path}: garbled response body") from e
        except (ConnectionError, socket.timeout, TimeoutError,
                OSError) as e:
            if isinstance(e, (socket.timeout, TimeoutError)):
                raise ReplicaTimeout(
                    f"{self.name}{path}: socket timeout") from e
            raise ReplicaDown(f"{self.name}{path}: {e}") from e

    # -- endpoints ----------------------------------------------------------

    def healthz(self, timeout: float = 2.0) -> dict:
        """``{"live": bool, "ready": bool, "state": str}`` — an HTTP
        answer of ANY kind is liveness; readiness is the front end's
        verdict (503 carries the non-ready state in its JSON body).

        When the body carries the replica's wall clock (``ts``), the
        answer additionally estimates ``clock_offset_s`` (replica −
        caller, midpoint method over this probe's request/response
        timestamps) and ``rtt_s`` — the distributed-trace alignment
        riding the probe the router already makes."""

        def offset_of(body: dict, t0: float, t1: float) -> dict:
            ts = body.get("ts")
            if ts is None:
                return {}
            return {"clock_offset_s": float(ts) - 0.5 * (t0 + t1),
                    "rtt_s": t1 - t0}

        t0 = time.time()
        try:
            out = self._request("/healthz", timeout=timeout)
            return {"live": True, "ready": bool(out.get("ok")),
                    "state": out.get("state", "ready"),
                    **offset_of(out, t0, time.time())}
        except ReplicaBusy as e:
            # 503 from /healthz = alive but NOT ready; the JSON body
            # carries the state (draining/staging_swap/slo_breach)
            return {"live": True, "ready": False,
                    "state": e.body.get("state", "not_ready"),
                    **offset_of(e.body, t0, time.time())}
        except ReplicaRejected:
            return {"live": True, "ready": False, "state": "error"}
        except (ReplicaDown, ReplicaTimeout):
            return {"live": False, "ready": False, "state": "dead"}

    def stats(self, timeout: float = 2.0) -> dict:
        return self._request("/stats", timeout=timeout)

    def generate(self, payload: dict,
                 timeout: Optional[float] = None) -> dict:
        """POST /v1/generate; returns the result dict only on a
        completed request — every other outcome is a ReplicaError the
        retry loop re-dispatches."""
        out = self._request("/v1/generate",
                            data=json.dumps(payload).encode(),
                            timeout=timeout)
        if out.get("state") != "done":
            raise ReplicaRejected(
                f"{self.name}: request ended state={out.get('state')!r}")
        return out

    def swap(self, checkpoint: str, timeout: float = 10.0) -> dict:
        return self._request(
            "/swap", data=json.dumps({"checkpoint": checkpoint}).encode(),
            timeout=timeout)


class ReplicaProcess(ReplicaClient):
    """A spawned serve subprocess + its client view."""

    def __init__(self, name: str, port: int, argv: List[str],
                 env: Optional[dict] = None, log_path: Optional[str] = None):
        super().__init__(name, port)
        self.argv = list(argv)
        self.env = dict(env) if env is not None else None
        self.log_path = log_path
        self.proc: Optional[subprocess.Popen] = None
        self._log_f = None
        self.paused = False

    def spawn(self) -> None:
        if self.log_path:
            os.makedirs(os.path.dirname(self.log_path) or ".",
                        exist_ok=True)
            self._log_f = open(self.log_path, "ab")
        self.proc = subprocess.Popen(
            self.argv, stdout=self._log_f or subprocess.DEVNULL,
            stderr=self._log_f or subprocess.DEVNULL, env=self.env)

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def wait_listening(self, timeout_s: float = 240.0,
                       poll_s: float = 0.25) -> bool:
        """Block until the replica answers /healthz at all (any state)
        or dies/times out — model init dominates startup; the first
        REQUEST pays the compiles."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if not self.alive:
                return False
            if self.healthz(timeout=2.0)["live"]:
                return True
            time.sleep(poll_s)
        return False

    # -- chaos / lifecycle ---------------------------------------------------

    def kill9(self) -> None:
        """The unhandleable death a preempted host actually gets."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.send_signal(signal.SIGKILL)
            self.proc.wait(timeout=30)

    def hang(self) -> None:
        """SIGSTOP: process alive, sockets unanswered — the gray
        failure liveness probes alone would miss."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.send_signal(signal.SIGSTOP)
            self.paused = True

    def resume(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.send_signal(signal.SIGCONT)
            self.paused = False

    def drain(self, timeout_s: float = 120.0) -> Optional[int]:
        """SIGTERM (the engine's drain path) and wait; SIGKILL
        escalation on overrun.  Returns the exit code."""
        if self.proc is None:
            return None
        if self.proc.poll() is None:
            if self.paused:
                self.resume()  # a stopped process cannot run its drain
            self.proc.send_signal(signal.SIGTERM)
            try:
                self.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                print(f"[fleet] {self.name}: drain overran "
                      f"{timeout_s:.0f}s, escalating to SIGKILL",
                      file=sys.stderr, flush=True)
                self.proc.kill()
                self.proc.wait(timeout=30)
        if self._log_f is not None:
            try:
                self._log_f.close()
            except OSError:
                pass
            self._log_f = None
        return self.proc.returncode

"""torchpruner_tpu — a TPU-native (JAX/XLA/pjit) structured-pruning framework.

A ground-up re-design of the capabilities of TorchPruner
(reference: /root/reference, see SURVEY.md) for TPU hardware:

- Models are :class:`~torchpruner_tpu.core.segment.SegmentedModel` specs —
  immutable, hashable layer pipelines whose ``prefix``/``suffix`` sub-programs
  compile to single XLA computations (replacing the reference's
  ``forward_partial`` convention, reference attributions.py:70-89).
- Attribution metrics (reference torchpruner/attributions/) are functional
  scorers built on ``jax.vjp``/``vmap``/``lax.scan`` instead of
  forward/backward hooks.
- Pruning (reference torchpruner/pruner/pruner.py) is functional
  re-instantiation: ``prune`` maps ``(model, params, state, opt_state)`` to new,
  smaller pytrees plus an updated static model spec; XLA recompiles at the new
  shapes ("on-the-fly" pruning, the XLA-honest way).
- Distribution is a first-class mesh layer (``torchpruner_tpu.parallel``):
  data-parallel attribution scoring and DP/FSDP fine-tuning via
  ``jax.sharding`` — collectives ride ICI, inserted by XLA.
"""

from torchpruner_tpu.core.segment import SegmentedModel, init_model
from torchpruner_tpu.core import layers
from torchpruner_tpu.core.graph import (
    pruning_graph,
    find_best_evaluation_layer,
    nan_cascade_oracle,
)
from torchpruner_tpu.core.plan import (
    Consumer,
    PlanError,
    PruneGroup,
    PrunePlan,
)
from torchpruner_tpu.core.masking import (
    apply_masks,
    drop_masks,
    masked_update,
)
from torchpruner_tpu.core.pruner import (
    Pruner,
    bucket_drop,
    prune,
    prune_by_scores,
)
from torchpruner_tpu.generate import (
    clear_generate_cache,
    generate,
    init_cache,
    make_decode_step,
    make_slot_decode_step,
)
from torchpruner_tpu.ops.quant import (
    QTensor,
    dequantize_params,
    quantize_params,
)
from torchpruner_tpu.utils.torch_import import (
    import_hf_llama,
    import_torch_vgg16_bn,
)
from torchpruner_tpu.attributions import (
    RandomAttributionMetric,
    WeightNormAttributionMetric,
    APoZAttributionMetric,
    SensitivityAttributionMetric,
    TaylorAttributionMetric,
    ShapleyAttributionMetric,
)

__version__ = "0.1.0"

__all__ = [
    "import_torch_vgg16_bn",
    "import_hf_llama",
    "SegmentedModel",
    "init_model",
    "layers",
    "pruning_graph",
    "find_best_evaluation_layer",
    "nan_cascade_oracle",
    "PruneGroup",
    "Consumer",
    "PrunePlan",
    "PlanError",
    "prune",
    "prune_by_scores",
    "bucket_drop",
    "apply_masks",
    "drop_masks",
    "masked_update",
    "clear_generate_cache",
    "generate",
    "init_cache",
    "make_decode_step",
    "make_slot_decode_step",
    "QTensor",
    "quantize_params",
    "dequantize_params",
    "Pruner",
    "RandomAttributionMetric",
    "WeightNormAttributionMetric",
    "APoZAttributionMetric",
    "SensitivityAttributionMetric",
    "TaylorAttributionMetric",
    "ShapleyAttributionMetric",
]

"""``python -m torchpruner_tpu serve`` — the serving endpoint.

Three front ends over one engine loop, all SIGTERM-drain-safe and obs-
instrumented (TTFT / per-token histograms, queue-depth / active-slot
gauges, ledger provenance records):

- ``--synthetic N`` — open-loop synthetic traffic (Poisson at
  ``--rate``, or deterministic ``--stagger-steps``); prints a JSON
  summary line.  ``--verify`` re-decodes every request alone through
  ``generate()`` and asserts token equality — the continuous-batching
  correctness contract, used by the CI smoke.
- ``--http PORT`` — a local HTTP endpoint: ``POST /v1/generate`` with
  ``{"prompt_ids": [...], "max_new": N, "temperature": ..,
  "top_k": .., "top_p": .., "seed": ..}`` blocks until the engine
  finishes the request and returns its tokens (or answers 503 +
  ``Retry-After`` when the ``--queue-bound``ed scheduler queue is
  full / a drain began — bounded backpressure, never an unbounded
  queue); ``GET /healthz`` splits liveness from READINESS (200 only
  when ``ready``; 503 carrying ``draining`` / ``staging_swap`` /
  ``slo_breach`` so probes and the fleet router stop dispatching
  early); ``GET /stats`` reports serving gauges (KV-page occupancy,
  slot utilization, rolling SLO state); ``GET /metrics``
  exposes the session's Prometheus text (scrapeable live, the same
  exposition ``metrics.prom`` holds at close); ``POST /profile`` arms
  one on-demand kernel-profiling capture window (``obs.profile``);
  ``POST /swap {"checkpoint": DIR}`` stages a zero-downtime hot-swap
  (the fleet upgrade loop's per-replica step).
- ``--stdin`` — one JSON request per line (same schema), results
  echoed as JSON lines; EOF drains and exits.

Examples::

    python -m torchpruner_tpu serve llama3_ffn_taylor --smoke --cpu \
        --synthetic 16 --verify --obs-dir logs/serve_obs
    python -m torchpruner_tpu serve llama_tiny --cpu --http 8811
    python -m torchpruner_tpu serve llama3_ffn_taylor \
        --checkpoint runs/prune/ckpt-000007-s00001200 --kv-dtype bfloat16
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
from typing import Optional

from torchpruner_tpu.serve.request import (
    DRAINED,
    SHED,
    request_from_dict,
)


def _resolve_model(name: str, *, smoke: bool, seed: int,
                   checkpoint: Optional[str]):
    """(model, params, meta): a digest-verified checkpoint when given,
    else the named preset's model (or a bare MODEL_REGISTRY name) with
    seeded init params."""
    if checkpoint:
        from torchpruner_tpu.checkpoint import restore_checkpoint

        model, params, _state, _opt, meta = restore_checkpoint(checkpoint)
        meta = dict(meta or {})
        meta["checkpoint"] = checkpoint
        return model, params, meta
    from torchpruner_tpu.core.segment import init_model
    from torchpruner_tpu.experiments.presets import PRESETS, get_preset
    from torchpruner_tpu.experiments.prune_retrain import MODEL_REGISTRY

    if name in PRESETS:
        model_name = get_preset(name, smoke=smoke).model
    elif name in MODEL_REGISTRY:
        model_name = name
    else:
        raise SystemExit(
            f"unknown preset/model {name!r}; presets: {list(PRESETS)}; "
            f"models: {list(MODEL_REGISTRY)}")
    model = MODEL_REGISTRY[model_name][0]()
    params, _state = init_model(model, seed=seed)
    return model, params, {"model": model_name}


#: the wire-schema parse lives with the Request type now
#: (serve.request.request_from_dict) — one schema for HTTP, stdin,
#: journal redrive, and the fleet router
_request_from_json = request_from_dict


def http_json(handler, code: int, payload: dict,
              headers: Optional[dict] = None) -> None:
    """The one JSON-response writer shared by the single-replica and
    fleet HTTP front ends (body + Content-Length + extra headers)."""
    body = json.dumps(payload).encode()
    handler.send_response(code)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(body)))
    for k, v in (headers or {}).items():
        handler.send_header(k, str(v))
    handler.end_headers()
    handler.wfile.write(body)


def retry_after_s(queue_depth: int, n_slots: int) -> int:
    """The 503 Retry-After hint: roughly how many scheduling waves the
    backlog represents (queue depth over the slot-array width), floored
    at one second — honest enough to spread thundering-herd retries
    without modeling decode time."""
    return max(1, int(round(queue_depth / max(1, n_slots))))


def _http_server(engine, port: int, request_timeout_s: float):
    """Threaded HTTP front end; handlers submit into the engine loop
    running on the main thread and block on the request's event."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet access log
            pass

        def _json(self, code: int, payload: dict,
                  headers: Optional[dict] = None):
            http_json(self, code, payload, headers)

        def _text(self, code: int, body: str,
                  content_type: str = "text/plain; version=0.0.4"):
            data = body.encode()
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            import time as _time

            if self.path == "/healthz":
                # liveness (we answered at all) split from READINESS:
                # non-ready states answer 503 so a k8s-style probe — and
                # the fleet router — stops dispatching here before a
                # drain completes / while a swap stages / during an SLO
                # breach episode.  "ts" is this replica's wall clock:
                # the router's clock-offset estimate (distributed trace
                # alignment) rides the health probe it already makes.
                state = engine.health_state()
                self._json(200 if state == "ready" else 503,
                           {"ok": state == "ready", "live": True,
                            "state": state, "ts": _time.time()})
            elif self.path == "/stats":
                sched = engine.scheduler
                alloc = sched.allocator
                stats = {
                    "state": engine.health_state(),
                    "ts": _time.time(),
                    # live queue age (submit -> admit) over the recent-
                    # admissions window — visible while requests are
                    # still waiting/decoding, not only at completion
                    "queue_wait_ms": sched.queue_wait_ms(),
                    "swaps": engine.swaps_total,
                    "queue_depth": sched.queue_depth,
                    "active_slots": alloc.active_slots,
                    "kv_pages_in_use": alloc.pages_in_use,
                    "kv_page_budget": alloc.page_budget,
                    "kv_page_occupancy": round(
                        alloc.pages_in_use / max(1, alloc.page_budget),
                        4),
                    "slot_utilization": round(
                        alloc.active_slots / max(1, alloc.n_slots), 4),
                    "decode_steps": engine.steps,
                    "gen_tokens": engine.gen_tokens,
                    "admits": sched.admitted_total,
                    "evictions": alloc.total_evictions,
                }
                if alloc.prefix_enabled:
                    stats["prefix"] = {
                        "hits": alloc.prefix_hits,
                        "misses": alloc.prefix_misses,
                        "hit_tokens": alloc.prefix_hit_tokens,
                        "pool_pages": alloc.prefix_pages,
                        "pool_used": alloc.prefix_pool_used,
                        "shared_pages": alloc.shared_pages,
                        "evictions": alloc.prefix_evictions,
                    }
                if engine.slo is not None:
                    stats["slo"] = engine.slo.snapshot()
                self._json(200, stats)
            elif self.path == "/metrics":
                # live Prometheus exposition of the obs session's
                # registry (obs/exporters.py) — the scrape target a real
                # deployment points at; 503 without a session
                from torchpruner_tpu import obs
                from torchpruner_tpu.obs.exporters import prometheus_text

                session = obs.get()
                if session is None:
                    self._text(503, "# no obs session (run with "
                                    "--obs-dir or without --no-obs)\n")
                    return
                if engine.slo is not None:
                    engine.slo.check(engine.steps)  # fresh rolling p99s
                self._text(200, prometheus_text(session.metrics))
            else:
                self._json(404, {"error": "not found"})

        def do_POST(self):
            if self.path == "/profile":
                from torchpruner_tpu import obs

                armed = obs.request_profile_window()
                self._json(202 if armed else 409, {
                    "armed": armed,
                    **({} if armed else
                       {"error": "no obs session/profiler, or a window "
                                 "is already open/armed"})})
                return
            if self.path == "/swap":
                # stage a checkpoint hot-swap (engine.request_swap) —
                # what makes a fleet upgrade a LOOP over replicas: the
                # router sees `staging_swap` readiness and rotates
                # traffic away until the swap lands
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    ckpt = json.loads(self.rfile.read(n))["checkpoint"]
                    engine.request_swap(str(ckpt))
                except (ValueError, KeyError,
                        json.JSONDecodeError) as e:
                    self._json(400, {"error": str(e)})
                    return
                except RuntimeError as e:  # a swap is already staging
                    self._json(409, {"error": str(e)})
                    return
                self._json(202, {"staging": True, "swaps": engine.swaps_total})
                return
            if self.path != "/v1/generate":
                self._json(404, {"error": "not found"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = _request_from_json(json.loads(self.rfile.read(n)))
                engine.submit(req)
            except (ValueError, KeyError, json.JSONDecodeError) as e:
                self._json(400, {"error": str(e)})
                return
            if req.state == SHED:
                # over-capacity: bounded-queue backpressure, never an
                # unbounded queue or a blocked accept loop
                sched = engine.scheduler
                self._json(503, {"error": "over capacity", "state": SHED,
                                 "queue_depth": sched.queue_depth},
                           headers={"Retry-After": retry_after_s(
                               sched.queue_depth, engine.n_slots)})
                return
            if req.state == DRAINED:
                # racing a drain: resubmit elsewhere (the fleet router
                # treats this exactly like the backpressure 503)
                self._json(503, {"error": "draining", "state": DRAINED},
                           headers={"Retry-After": 1})
                return
            if not req.wait(timeout=request_timeout_s):
                self._json(504, {"error": "timed out", "id": req.id})
                return
            if req.state == DRAINED:
                # drained AFTER queueing (SIGTERM mid-wait)
                self._json(503, {"error": "draining", "state": DRAINED,
                                 "id": req.id},
                           headers={"Retry-After": 1})
                return
            self._json(200, req.result())

    return ThreadingHTTPServer(("127.0.0.1", port), Handler)


def serve_main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="torchpruner_tpu serve",
        description="continuous-batching inference engine on the pruned "
                    "decode path (scheduler + bucketed KV allocator + "
                    "prefill/decode disaggregation + hot-swap)")
    p.add_argument("preset", help="preset name (its model is served), a "
                                  "MODEL_REGISTRY model name, or anything "
                                  "with --checkpoint")
    p.add_argument("--checkpoint", metavar="DIR",
                   help="serve this digest-verified checkpoint (restores "
                        "the PRUNED spec + params) instead of seeded "
                        "init params")
    p.add_argument("--smoke", action="store_true",
                   help="preset's miniature model variant")
    p.add_argument("--cpu", action="store_true",
                   help="force the CPU backend")
    p.add_argument("--slots", type=int, default=4,
                   help="decode slot-array width (compiled batch)")
    p.add_argument("--max-len", type=int, default=256,
                   help="KV positions per slot (prompt + max_new cap)")
    p.add_argument("--kv-dtype", choices=("float32", "bfloat16"),
                   default="float32",
                   help="KV-cache dtype; bfloat16 halves cache HBM "
                        "(the serving config)")
    p.add_argument("--page-len", type=int, default=0,
                   help="KV page size (0 = lane-aligned default)")
    p.add_argument("--prefix-pages", type=int, default=0,
                   help="Serve v2: device pages reserved for the shared-"
                        "prefix pool (radix-trie prefix cache; 0 = "
                        "sharing off).  Prompts matching a published "
                        "prefix copy whole pages instead of re-running "
                        "prefill.")
    p.add_argument("--prefill-chunk", type=int, default=0,
                   help="Serve v2: chunked-prefill width in tokens (must "
                        "divide max-len and page-len; 0 = legacy whole-"
                        "bucket prefill, or an auto gcd pick when "
                        "--prefix-pages is on)")
    p.add_argument("--prefill-cap", type=int, default=0,
                   help="Serve v2: per-engine-step prefill-token budget "
                        "(floored at one chunk) so long prompts can't "
                        "starve resident decodes; 0 = uncapped")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--run-dir", metavar="DIR",
                   help="where the SIGTERM drain snapshots the queue")
    p.add_argument("--obs-dir", metavar="DIR",
                   help="runtime telemetry directory (events/metrics/"
                        "ledger/report; see `obs report`)")
    p.add_argument("--no-obs", action="store_true")
    p.add_argument("--profile-every", type=int, default=None,
                   metavar="N",
                   help="with --obs-dir: kernel-profiling capture window "
                        "every N decode steps (obs.profile; `obs profile "
                        "<obs-dir>` renders the table; the HTTP frontend "
                        "can also arm one via POST /profile)")
    p.add_argument("--profile-steps", type=int, default=None, metavar="K",
                   help="decode steps per capture window (default 3)")
    p.add_argument("--slo-ttft-p99-ms", type=float, default=None,
                   help="live SLO threshold: rolling TTFT p99 above this "
                        "counts a breach episode (serve_slo_breach_total"
                        ", ledgered)")
    p.add_argument("--slo-token-p99-ms", type=float, default=None,
                   help="live SLO threshold: rolling per-token p99 (ms)")
    p.add_argument("--slo-queue-p99-ms", type=float, default=None,
                   help="live SLO threshold: rolling queue-age-at-"
                        "admission p99 (ms)")
    p.add_argument("--slo-window", type=int, default=256,
                   help="observations in the rolling SLO window")
    p.add_argument("--swap-checkpoint", metavar="DIR",
                   help="hot-swap to this checkpoint mid-run (synthetic "
                        "mode: staged after --swap-after steps)")
    p.add_argument("--swap-after", type=int, default=8,
                   help="engine steps before staging --swap-checkpoint")
    mode = p.add_mutually_exclusive_group()
    mode.add_argument("--synthetic", type=int, metavar="N",
                      help="serve N open-loop synthetic requests, print "
                           "a JSON summary, exit")
    mode.add_argument("--http", type=int, metavar="PORT",
                      help="serve a local HTTP endpoint")
    mode.add_argument("--stdin", action="store_true",
                      help="read JSON requests from stdin")
    p.add_argument("--rate", type=float, default=0.0,
                   help="synthetic: Poisson arrival rate, requests/s "
                        "(0 = deterministic step staggering)")
    p.add_argument("--stagger-steps", type=int, default=2,
                   help="synthetic: steps between deterministic arrivals")
    p.add_argument("--prompt-lens", default="4,8,6",
                   help="synthetic: comma list of prompt lengths (cycled)"
                        "; with --shared-prefixes these are the SUFFIX "
                        "lengths after the shared prefix")
    p.add_argument("--shared-prefixes", type=int, default=0, metavar="K",
                   help="synthetic: draw prompts from a pool of K shared "
                        "system prompts (round-robin) + random suffixes "
                        "— the prefix-heavy workload; 0 = fully random "
                        "prompts")
    p.add_argument("--prefix-len", type=int, default=32,
                   help="synthetic: shared system-prompt length in "
                        "tokens (with --shared-prefixes)")
    p.add_argument("--sessions", type=int, default=0,
                   help="synthetic: tag requests with round-robin "
                        "session ids (with --shared-prefixes) — the "
                        "fleet router's session-affinity signal")
    p.add_argument("--max-new", default="8,5,12",
                   help="synthetic: comma list of generation budgets")
    p.add_argument("--temperature", type=float, default=0.0,
                   help="synthetic: sampling temperature (0 = greedy)")
    p.add_argument("--verify", action="store_true",
                   help="synthetic: assert every request's tokens equal "
                        "its solo generate() decode (the continuous-"
                        "batching correctness contract)")
    p.add_argument("--timeout", type=float, default=120.0,
                   help="http: per-request wait timeout (seconds)")
    p.add_argument("--trace-sample-every", type=int, default=None,
                   metavar="N",
                   help="request-trace exemplar policy (obs.reqtrace): "
                        "flush full stage detail for 1-in-N requests "
                        "(deterministic on the trace id) plus the "
                        "slowest-K per window; 1 = eager full tracing "
                        "(drills); default 16 / env "
                        "TORCHPRUNER_REQTRACE_SAMPLE_EVERY")
    p.add_argument("--queue-bound", type=int, default=0,
                   help="bound the scheduler's waiting queue: a "
                        "submission landing on a full queue is shed "
                        "with 503 + Retry-After "
                        "(serve_rejected_backpressure_total) instead "
                        "of queueing unboundedly; 0 = unbounded "
                        "(batch modes).  The fleet router passes its "
                        "own bound here.")
    p.add_argument("--tenants", metavar="JSON",
                   help="multi-tenant QoS policy table (serve.qos), "
                        "e.g. '{\"chat\": {\"priority\": "
                        "\"interactive\"}, \"batch\": {\"priority\": "
                        "\"batch\", \"rate\": 4, \"burst\": 8, "
                        "\"page_quota\": 16}}' — per-tenant token "
                        "buckets, strict step-boundary priority "
                        "preemption, KV-page quotas; requests opt in "
                        "via their \"tenant\" field")
    args = p.parse_args(argv)

    if args.profile_every is not None and not args.obs_dir:
        p.error("--profile-every needs --obs-dir (the capture windows "
                "live under it)")

    # TORCHPRUNER_CHAOS env → serving faults (slow_steps_ms: the fleet
    # drill's "slow replica"); installs nothing when unset
    from torchpruner_tpu.resilience import chaos as chaos_mod

    chaos_mod.configure(None)

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from torchpruner_tpu import obs
    from torchpruner_tpu.resilience.guards import PreemptionHandler
    from torchpruner_tpu.serve.engine import ServeEngine

    session = None
    if not args.no_obs:
        session = obs.configure(args.obs_dir,
                                profile_every=args.profile_every,
                                profile_steps=args.profile_steps)
        obs.annotate_run(experiment=f"serve:{args.preset}", kind="serve",
                         model=args.preset,
                         checkpoint=args.checkpoint or "")
    if args.trace_sample_every is not None:
        from torchpruner_tpu.obs import reqtrace

        reqtrace.configure(sample_every=args.trace_sample_every)

    qos = None
    if args.tenants:
        from torchpruner_tpu.serve.qos import QoS

        try:
            qos = QoS.from_dict(json.loads(args.tenants))
        except (ValueError, TypeError, KeyError,
                json.JSONDecodeError) as e:
            p.error(f"--tenants: {e}")

    model, params, meta = _resolve_model(
        args.preset, smoke=args.smoke, seed=args.seed,
        checkpoint=args.checkpoint)
    engine = ServeEngine(
        model, params, n_slots=args.slots, max_len=args.max_len,
        cache_dtype=(jnp.bfloat16 if args.kv_dtype == "bfloat16"
                     else jnp.float32),
        page_len=args.page_len, run_dir=args.run_dir,
        prefix_pages=args.prefix_pages, prefill_chunk=args.prefill_chunk,
        prefill_token_cap=args.prefill_cap, qos=qos,
        checkpoint_meta=meta, queue_bound=args.queue_bound,
        # a long-running HTTP server must not accumulate completed
        # requests (each pins its prompt/tokens and, across a swap, the
        # old program set); batch modes need them for verify/reporting
        retain_results=args.http is None)
    if args.slo_ttft_p99_ms is not None \
            or args.slo_token_p99_ms is not None \
            or args.slo_queue_p99_ms is not None:
        from torchpruner_tpu.serve.slo import SLOMonitor

        engine.slo = SLOMonitor(
            ttft_p99_s=(args.slo_ttft_p99_ms / 1e3
                        if args.slo_ttft_p99_ms is not None else None),
            token_p99_s=(args.slo_token_p99_ms / 1e3
                         if args.slo_token_p99_ms is not None else None),
            queue_p99_s=(args.slo_queue_p99_ms / 1e3
                         if args.slo_queue_p99_ms is not None else None),
            window=args.slo_window)

    rc = 0
    try:
        # obs.span degrades to a nullcontext without a session
        with PreemptionHandler() as pre, \
                obs.span("serve", preset=args.preset):
            if args.http is not None:
                rc = _run_http(engine, pre, args)
            elif args.stdin:
                rc = _run_stdin(engine, pre, args)
            else:
                rc = _run_synthetic(engine, pre, args, model, params)
    finally:
        if session is not None:
            obs.shutdown(print_to=sys.stderr)
            if args.obs_dir:
                print(f"telemetry written to {args.obs_dir}",
                      file=sys.stderr)
    return rc


def _run_synthetic(engine, pre, args, model, params) -> int:
    from torchpruner_tpu.serve.traffic import (
        open_loop,
        shared_prefix_requests,
        synthetic_requests,
    )

    from torchpruner_tpu.serve.engine import vocab_of

    n = args.synthetic or 8
    vocab = vocab_of(model)
    prompt_lens = [int(x) for x in args.prompt_lens.split(",") if x]
    max_new = [int(x) for x in args.max_new.split(",") if x]
    if args.shared_prefixes > 0:
        reqs = shared_prefix_requests(
            n, vocab=vocab, n_prefixes=args.shared_prefixes,
            prefix_len=args.prefix_len, suffix_lens=prompt_lens,
            max_new=max_new, seed=args.seed, sessions=args.sessions,
            temperature=args.temperature)
    else:
        reqs = synthetic_requests(
            n, vocab=vocab, prompt_lens=prompt_lens, max_new=max_new,
            seed=args.seed, temperature=args.temperature)
    # ONE arrival-process selector shared with the bench serve legs and
    # the fleet workload replayer (serve.traffic.open_loop): Poisson at
    # --rate, else deterministic step staggering
    traffic = open_loop(reqs, rate=args.rate,
                        stagger_steps=args.stagger_steps,
                        seed=args.seed)
    if args.swap_checkpoint:
        traffic = _SwapAt(traffic, args.swap_checkpoint, args.swap_after)
    # sync line for wrappers (the CI SIGTERM drill keys off it): printed
    # BEFORE the first admission, i.e. before any compile
    print(f"serve: engine loop starting ({n} synthetic requests, "
          f"{engine.n_slots} slots)", file=sys.stderr, flush=True)
    summary = engine.run(traffic, preemption=pre)
    summary["drained_snapshot"] = len(engine.drained)
    if args.verify:
        import jax
        import numpy as np

        from torchpruner_tpu.generate import generate

        mismatches = 0
        for r in engine.results():
            s = r.sampling
            # replay against the program set that actually served the
            # request (a hot-swap mid-run changes engine.params; the
            # request carries its own)
            P = r.served_by or engine.programs
            # max_len pins the replay to the SERVING cache geometry:
            # the decode kernel's block partition is a function of the
            # cache length (ops/decode_attention.py), so bit-identity
            # requires replaying at the engine's max_len
            want = generate(
                P.model, P.params, r.prompt_ids[None],
                r.max_new, temperature=s.temperature, top_k=s.top_k,
                top_p=s.top_p, rng=jax.random.PRNGKey(s.seed),
                cache_dtype=P.cache_dtype, max_len=P.max_len)
            if not np.array_equal(np.asarray(r.tokens, np.int32),
                                  np.asarray(want)[0][:len(r.tokens)]):
                mismatches += 1
        summary["verify_mismatches"] = mismatches
        if mismatches:
            print(json.dumps(summary))
            print(f"VERIFY FAILED: {mismatches} requests diverged from "
                  "solo decode", file=sys.stderr)
            return 1
    print(json.dumps(summary))
    return 0


class _SwapAt:
    """Traffic wrapper staging a hot-swap after N engine steps."""

    def __init__(self, inner, checkpoint: str, after_steps: int):
        self.inner, self.checkpoint = inner, checkpoint
        self.after_steps, self.fired = after_steps, False

    @property
    def exhausted(self):
        return self.inner.exhausted

    def drain(self):
        return self.inner.drain()

    def pump(self, engine):
        n = self.inner.pump(engine)
        if not self.fired and engine.steps >= self.after_steps:
            engine.request_swap(self.checkpoint)
            self.fired = True
        return n


def _run_stdin(engine, pre, args) -> int:
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            engine.submit(_request_from_json(json.loads(line)))
        except (ValueError, KeyError, json.JSONDecodeError) as e:
            print(json.dumps({"error": str(e)}), flush=True)
    summary = engine.run(preemption=pre)
    for r in engine.results():
        print(json.dumps(r.result()), flush=True)
    print(json.dumps(summary), file=sys.stderr)
    return 0


def _run_http(engine, pre, args) -> int:
    server = _http_server(engine, args.http, args.timeout)
    stop = threading.Event()
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    print(f"serving on http://127.0.0.1:{args.http} "
          f"(POST /v1/generate, GET /healthz /stats)", file=sys.stderr,
          flush=True)
    summary = None
    try:
        # the engine loop owns the main thread; SIGTERM drains in-flight
        # requests, snapshots the queue, and returns
        summary = engine.run(preemption=pre, stop_event=stop)
    finally:
        server.shutdown()
        t.join(timeout=5)
    print(json.dumps(summary if summary is not None
                     else engine.summary()), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(serve_main())

"""Request/response types for the continuous-batching serving engine.

A :class:`Request` is one generation job: prompt token ids, a budget of
new tokens, and per-request :class:`Sampling` parameters.  The engine
mutates the request in place as it moves through the lifecycle
(``QUEUED → ACTIVE → DONE``), appending generated tokens and stamping
the latency timestamps the obs histograms are built from (TTFT =
first-token wall time from arrival; per-token = gap between successive
tokens of the SAME request, which under continuous batching includes
any steps the request spent sharing the slot array).
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

_ids = itertools.count()


@dataclass(frozen=True)
class Sampling:
    """Per-request sampling config — the same semantics as
    :func:`torchpruner_tpu.generate.generate`: greedy at
    ``temperature == 0`` (exact argmax, the bit-parity contract with
    solo decode), else seeded softmax sampling optionally truncated to
    ``top_k`` / the ``top_p`` nucleus.  ``seed`` pins the request's rng
    stream so a request replayed alone reproduces its tokens."""

    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    seed: int = 0

    def validate(self, vocab: int) -> None:
        if self.temperature < 0.0:
            raise ValueError(f"temperature must be >= 0, "
                             f"got {self.temperature}")
        if self.top_k is not None and not (1 <= self.top_k):
            raise ValueError(f"top_k must be >= 1, got {self.top_k}")
        if self.top_p is not None and not (0.0 < self.top_p <= 1.0):
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")


# lifecycle states
QUEUED = "queued"      # submitted, waiting for a slot
ACTIVE = "active"      # holds a slot (prefilled, decoding)
DONE = "done"          # emitted max_new tokens (or eos)
DRAINED = "drained"    # never started; snapshotted at drain
SHED = "shed"          # rejected at admission (queue over its bound)


@dataclass
class Request:
    """One generation job.  ``prompt_ids`` is a 1-D int sequence;
    ``max_new`` the generation budget; ``eos_id`` an optional early-stop
    token.  ``arrival_s`` is stamped by the scheduler at submit (or
    carried in by an open-loop traffic generator whose arrival schedule
    is the experiment)."""

    prompt_ids: np.ndarray
    max_new: int
    sampling: Sampling = field(default_factory=Sampling)
    eos_id: Optional[int] = None
    id: int = field(default_factory=lambda: next(_ids))

    #: fleet-minted distributed trace id (obs.reqtrace) — propagated in
    #: the dispatch payload so replica-side stage events join the
    #: router's on one cross-process waterfall; None = untraced
    trace_id: Optional[str] = None

    #: caller-asserted session key — the fleet router's strongest
    #: prefix-affinity signal (requests of one session share a growing
    #: prompt prefix, so landing them on one replica compounds its
    #: prefix-cache hits); None = route by prompt-page hash / load only
    session_id: Optional[str] = None

    #: QoS tenant (serve.qos): traffic class for token-bucket
    #: throttling, priority admission/preemption and KV-page quotas;
    #: None = the unthrottled interactive default
    tenant: Optional[str] = None

    # -- engine-owned runtime state ------------------------------------
    state: str = QUEUED
    slot: Optional[int] = None
    tokens: List[int] = field(default_factory=list)
    arrival_s: Optional[float] = None
    #: when the scheduler granted the slot (queue-age = admitted - arrival)
    admitted_s: Optional[float] = None
    #: wall seconds the prefill program (+ cache insert) took
    prefill_s: Optional[float] = None
    first_token_s: Optional[float] = None
    done_s: Optional[float] = None
    #: wall-clock gaps between successive tokens (len == tokens - 1)
    token_gaps_s: List[float] = field(default_factory=list)
    #: times this request was preempted back to the queue by a
    #: higher-priority admission (qos) — progress restarts on re-admit
    preemptions: int = 0
    #: prompt tokens served by mapping shared prefix pages (0 = miss
    #: or sharing off) / actually computed by prefill programs —
    #: stamped by the engine; hit + prefilled == prompt_len on the
    #: chunked path
    prefix_hit_tokens: int = 0
    prefilled_tokens: int = 0
    #: the program set (checkpoint) that decoded this request — stamped
    #: at prefill so verification replays against the RIGHT weights
    #: even when a hot-swap landed mid-run
    served_by: Optional[object] = field(default=None, repr=False)
    #: completion signal for frontends blocking on the result
    _event: threading.Event = field(default_factory=threading.Event,
                                    repr=False)

    def __post_init__(self):
        self.prompt_ids = np.asarray(self.prompt_ids,
                                     np.int32).reshape(-1)
        if self.prompt_ids.size == 0:
            raise ValueError("empty prompt")
        if self.max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {self.max_new}")

    @property
    def total_len(self) -> int:
        """Positions the request needs resident in its slot's cache."""
        return int(self.prompt_ids.size) + int(self.max_new)

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_s is None or self.arrival_s is None:
            return None
        return self.first_token_s - self.arrival_s

    def finished(self) -> bool:
        return self.state in (DONE, DRAINED)

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the engine completes (or drains) this request —
        the HTTP frontend's hand-off from handler thread to engine
        loop."""
        return self._event.wait(timeout)

    def result(self) -> dict:
        return {
            "id": self.id,
            "state": self.state,
            "tokens": list(self.tokens),
            "prompt_len": int(self.prompt_ids.size),
            "ttft_s": self.ttft_s,
            "token_gaps_s": list(self.token_gaps_s),
            "prefix_hit_tokens": int(self.prefix_hit_tokens),
        }

    def snapshot(self) -> dict:
        """JSON form for the drain snapshot — enough to resubmit the
        request verbatim after a preemption."""
        return {
            "prompt_ids": self.prompt_ids.tolist(),
            "max_new": int(self.max_new),
            "eos_id": self.eos_id,
            "session_id": self.session_id,
            "tenant": self.tenant,
            "sampling": {
                "temperature": self.sampling.temperature,
                "top_k": self.sampling.top_k,
                "top_p": self.sampling.top_p,
                "seed": self.sampling.seed,
            },
        }

    @classmethod
    def from_snapshot(cls, d: dict) -> "Request":
        return cls(prompt_ids=np.asarray(d["prompt_ids"], np.int32),
                   max_new=int(d["max_new"]), eos_id=d.get("eos_id"),
                   session_id=d.get("session_id"),
                   tenant=d.get("tenant"),
                   sampling=Sampling(**(d.get("sampling") or {})))


def request_from_dict(d: dict) -> Request:
    """The ONE wire schema → :class:`Request` parse, shared by every
    transport (single-replica HTTP/stdin front ends, the fleet router's
    dispatch, journal redrive): ``{"prompt_ids": [...], "max_new": N,
    "eos_id": ..., "temperature": .., "top_k": .., "top_p": ..,
    "seed": ..}`` — flat sampling fields, matching ``POST
    /v1/generate``."""
    return Request(
        prompt_ids=d["prompt_ids"], max_new=int(d.get("max_new", 16)),
        eos_id=d.get("eos_id"),
        # the router injects the fleet trace id at dispatch; absent on
        # direct/journal submissions (untraced)
        trace_id=d.get("trace_id"),
        session_id=d.get("session_id"),
        tenant=d.get("tenant"),
        sampling=Sampling(
            temperature=float(d.get("temperature", 0.0)),
            top_k=d.get("top_k"), top_p=d.get("top_p"),
            seed=int(d.get("seed", 0))))

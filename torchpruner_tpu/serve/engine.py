"""The continuous-batching inference engine on the pruned decode path.

One :class:`ServeEngine` owns a fixed ``n_slots``-wide decode slot
array and THREE compiled programs (prefill/decode disaggregation):

- **decode** — ONE jitted step advances every slot one token at its own
  per-slot position (``generate.make_slot_decode_step`` semantics plus
  fused per-slot sampling): admissions and evictions at step boundaries
  only change host-side slot tables, never the executable, so a ragged
  ever-changing mix of requests rides a single XLA program.
- **prefill** — per lane-aligned prompt bucket (allocator ladder), a
  jitted whole-prompt forward fills a length-``bucket`` B=1 cache, takes
  the last REAL position's logits, and samples the first token.  End
  padding needs no masking: padded positions only write K/V at
  ``t >= true_len``, and decode overwrites position ``t`` before it
  first becomes attendable.
- **insert** — the hand-off: the bucket-length prefill cache is written
  into the slot's rows of the big ``(n_slots, max_len, ...)`` serving
  cache with one ``dynamic_update_slice`` per buffer (no retrace, no
  host copy of the cache).

**Serve v2** (``prefill_chunk > 0`` and/or ``prefix_pages > 0``) adds
two more program families, both shape-bounded the same way the bucket
ladder is:

- **chunked prefill** — a long prompt prefills in fixed lane-aligned
  chunks INTERLEAVED with decode steps (one compiled chunk program per
  chunk width, specialized by jit's shape cache), under a per-step
  prefill-token cap (scheduler) so a long prompt can never stall the
  slot array.  Every chunk boundary is a GLOBAL multiple of the chunk
  width (``chunk | page_len | max_len``), so two requests sharing a
  prefix apply byte-identical program/position pairs over it — the
  property that makes published prefix pages canonical.  While a slot
  is mid-prefill its decode write row is parked on ``max_len - 1``
  (never attendable before decode overwrites it) and its step outputs
  are discarded.
- **prefix page map/publish** — admission-time prefix hits copy pool
  pages into the slot's rows (one ``dynamic_slice`` +
  ``dynamic_update_slice`` per page per buffer); completion publishes
  the prompt's whole pages back into the pool.  The map is a COPY
  (copy-on-write materialized at admission): decode writes stay in the
  slot's private rows, the pool page stays canonical, and the compiled
  decode/prefill programs never learn about pages at all — sharing is
  pure host bookkeeping + bounded copy programs, which is how it fits
  the static-shape TPU contract.

Decode shapes ride the pruned model spec exactly like ``generate``:
pruning FFN channels / heads / experts shrinks the compiled programs and
the KV buffers with no serving-specific surgery — the runtime exploits
pruned structure, which is the whole point (PAPERS.md, "Structured Model
Pruning ... on TPUs").

**Hot-swap**: ``request_swap(ckpt_dir)`` stages a digest-verified
checkpoint (resilience-layer restore) on a BACKGROUND thread —
restore + compile + warm never block the engine loop, so in-flight
requests keep decoding at full cadence while the new programs build
(the "compiled off the serving path" contract; the span tracer's
per-thread stack keeps the ``serve_swap_compile`` span clean).  Once
staged, admissions drain, in-flight requests finish on the old weights
(their KV holds old-weight K/V — mixing checkpoints mid-sequence would
corrupt them), and traffic switches at the first empty-slot-array step
boundary.  The swap is ledgered with both checkpoints' digests.

**Drain** (SIGTERM): the engine polls the resilience layer's
:class:`~torchpruner_tpu.resilience.guards.PreemptionHandler` at step
boundaries — preemption stops admissions, finishes in-flight requests,
snapshots the still-queued ones to ``serve_queue_snapshot.json``
(atomic), and returns cleanly.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from torchpruner_tpu import obs
from torchpruner_tpu.obs import reqtrace
from torchpruner_tpu.resilience import chaos as _chaos
from torchpruner_tpu.serve.allocator import (
    KVCacheAllocator,
    bucket_for,
    prefill_buckets,
)
from torchpruner_tpu.serve.request import DONE, DRAINED, Request
from torchpruner_tpu.serve.scheduler import Scheduler

SNAPSHOT_FILENAME = "serve_queue_snapshot.json"


def vocab_of(model) -> int:
    """The model's token-id space (its Embedding layer's vocab) — what
    synthetic traffic draws prompt ids from."""
    from torchpruner_tpu.core import layers as L

    for spec in model.layers:
        if isinstance(spec, L.Embedding):
            return int(spec.vocab_size)
    return 256


def sample_tokens(logits, keys, temp, top_k, top_p):
    """Vectorized per-slot sampling: greedy (exact argmax — the
    bit-parity contract) where ``temp == 0``, else seeded softmax
    sampling at ``temp`` truncated per slot to ``top_k`` (``<= 0``
    disables) and the ``top_p`` nucleus (``>= 1`` disables).  Matches
    :func:`torchpruner_tpu.generate._truncate_logits` semantics
    (temperature first, same kth/nucleus thresholds) so a request
    replayed through ``generate`` with the same seed emits the same
    tokens."""
    import jax
    import jax.numpy as jnp

    V = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.where(temp > 0, temp, 1.0)[:, None]
    neg = jnp.finfo(logits.dtype).min
    # top-k FIRST, nucleus on the top-k-truncated distribution — the
    # exact order _truncate_logits applies (the nucleus mass must be
    # measured over the distribution actually sampled from)
    k = jnp.where(top_k > 0, top_k, V)
    kth = jnp.take_along_axis(
        jnp.sort(scaled, axis=-1)[..., ::-1],
        jnp.clip(k - 1, 0, V - 1)[:, None], axis=-1)
    masked = jnp.where(scaled >= kth, scaled, neg)
    sorted_ = jnp.sort(masked, axis=-1)[..., ::-1]  # descending
    probs = jax.nn.softmax(sorted_, axis=-1)
    csum = jnp.cumsum(probs, axis=-1)
    keep_sorted = (csum - probs) < top_p[:, None]
    thresh = jnp.min(jnp.where(keep_sorted, sorted_, jnp.inf), axis=-1,
                     keepdims=True)
    # a disabled nucleus (p >= 1) must keep EVERYTHING top-k kept,
    # including prob-underflow tails the threshold math could clip
    trunc = jnp.where((masked >= thresh) | (top_p[:, None] >= 1.0),
                      masked, neg)
    sampled = jax.vmap(jax.random.categorical)(keys, trunc)
    return jnp.where(temp > 0, sampled.astype(jnp.int32), greedy)


def make_serve_step(model):
    """jit: one continuous-batching decode step with fused sampling —
    ``(params, cache, tok (B,), pos (B,), rngs (B,2), temp (B,),
    top_k (B,), top_p (B,)) -> (next_tok (B,), rngs', cache')``."""
    import jax

    from torchpruner_tpu.generate import _decode_seq

    @jax.jit
    def step(params, cache, tok, pos, rngs, temp, top_k, top_p):
        x, cache = _decode_seq(model.layers, params, cache, tok[:, None],
                               pos)
        logits = x[:, 0]
        split = jax.vmap(jax.random.split)(rngs)  # (B, 2, 2)
        carry, sub = split[:, 0], split[:, 1]
        nxt = sample_tokens(logits, sub, temp, top_k, top_p)
        return nxt, carry, cache

    return step


def make_prefill(model, bucket: int, cache_dtype):
    """jit: bucketed-length prefill — ``(params, prompt (1, bucket),
    true_len, rng (2,), temp, top_k, top_p) -> (first_tok, rng',
    bucket_cache)``.  One compiled program per (model spec, bucket)."""
    import jax
    import jax.numpy as jnp

    from torchpruner_tpu.generate import _decode_seq, init_cache

    @jax.jit
    def prefill(params, prompt, true_len, rng, temp, top_k, top_p):
        cache = init_cache(model, 1, bucket, cache_dtype)
        x, cache = _decode_seq(model.layers, params, cache, prompt, 0)
        logits = jnp.take(x[0], true_len - 1, axis=0)  # last REAL position
        carry, sub = jax.random.split(rng)
        tok = sample_tokens(logits[None], sub[None], temp[None],
                            top_k[None], top_p[None])[0]
        return tok, carry, cache

    return prefill


def make_insert():
    """jit: write a bucket-length B=1 prefill cache into one slot's rows
    of the big serving cache (the prefill→decode hand-off)."""
    import jax
    from jax import lax

    @jax.jit
    def insert(big, small, slot):
        def upd(b, s):
            return lax.dynamic_update_slice(
                b, s.astype(b.dtype), (slot, 0, 0, 0))

        return jax.tree_util.tree_map(upd, big, small)

    return insert


def default_prefill_chunk(max_len: int, page_len: int) -> int:
    """The largest lane-ladder chunk width dividing BOTH the slot
    length and the page size — divisibility is what keeps every chunk
    write in-bounds (no ``dynamic_update_slice`` clamping) and every
    chunk boundary globally aligned across requests (the prefix-page
    canonicality requirement)."""
    import math

    g = math.gcd(int(max_len), int(page_len))
    for c in (64, 32, 16, 8):
        if g % c == 0:
            return c
    return g


def make_chunk_prefill(model):
    """jit: one prefill chunk in place — ``(params, big_cache,
    toks (1, chunk), slot, pos0) -> (chunk logits (chunk, V),
    big_cache')``.  The slot's rows are sliced out as a B=1 cache, the
    chunk runs ``_decode_seq`` at absolute ``pos0`` (causal within the
    block, masked against everything beyond — padded tail positions
    write junk K/V at ``t >= prompt_len`` that decode overwrites before
    it is ever attendable, the same argument as bucket end-padding),
    and the rows are written back.  jit's shape cache yields one
    compiled program per chunk width, never one per prompt."""
    import jax
    from jax import lax

    from torchpruner_tpu.generate import _decode_seq

    @jax.jit
    def chunk(params, big, toks, slot, pos0):
        def rows(b):
            return lax.dynamic_slice(
                b, (slot, 0, 0, 0), (1,) + b.shape[1:])

        small = jax.tree_util.tree_map(rows, big)
        x, small = _decode_seq(model.layers, params, small, toks, pos0)

        def put(b, s):
            return lax.dynamic_update_slice(
                b, s.astype(b.dtype), (slot, 0, 0, 0))

        big = jax.tree_util.tree_map(put, big, small)
        return x[0], big

    return chunk


def make_page_copy(page_len: int):
    """jit pair moving one K/V page between the serving cache and the
    prefix pool: ``map_page(big, pool, page, slot, start) -> big'``
    (admission hit: pool page copied into the slot's rows — the
    copy-on-write materialization) and ``publish_page(pool, big, slot,
    start, page) -> pool'`` (completion: a freshly prefilled whole page
    published for future requests)."""
    import jax
    from jax import lax

    @jax.jit
    def map_page(big, pool, page, slot, start):
        def upd(b, p):
            blk = lax.dynamic_slice(
                p, (page, 0, 0, 0),
                (1, page_len, p.shape[2], p.shape[3]))
            return lax.dynamic_update_slice(
                b, blk.astype(b.dtype), (slot, start, 0, 0))

        return jax.tree_util.tree_map(upd, big, pool)

    @jax.jit
    def publish_page(pool, big, slot, start, page):
        def upd(p, b):
            blk = lax.dynamic_slice(
                b, (slot, start, 0, 0),
                (1, page_len, b.shape[2], b.shape[3]))
            return lax.dynamic_update_slice(
                p, blk.astype(p.dtype), (page, 0, 0, 0))

        return jax.tree_util.tree_map(upd, pool, big)

    return map_page, publish_page


def make_sample_at():
    """jit: sample the FIRST token from a chunk's logits block at the
    prompt's last real position — the same split/truncate/sample
    sequence :func:`make_prefill` fuses, so a chunked prefill emits the
    bit-identical first token for the same seed."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def sample_at(logits, idx, rng, temp, top_k, top_p):
        row = jnp.take(logits, idx, axis=0)
        carry, sub = jax.random.split(rng)
        tok = sample_tokens(row[None], sub[None], temp[None],
                            top_k[None], top_p[None])[0]
        return tok, carry

    return sample_at


class _Programs:
    """One checkpoint's compiled surface: model + params + serving cache
    + the three program families.  Swappable as a unit — hot-swap builds
    a fresh ``_Programs`` and warms it before any traffic touches it."""

    def __init__(self, model, params, *, n_slots: int, max_len: int,
                 cache_dtype, meta: Optional[dict] = None,
                 page_len: int = 0, prefix_pages: int = 0,
                 prefill_chunk: int = 0):
        import jax.numpy as jnp

        from torchpruner_tpu.generate import init_cache

        self.model, self.params, self.meta = model, params, dict(meta or {})
        self.n_slots, self.max_len = n_slots, max_len
        self.cache_dtype = cache_dtype
        self.cache = init_cache(model, n_slots, max_len, cache_dtype)
        self.decode = make_serve_step(model)
        self.insert = make_insert()
        self.buckets = prefill_buckets(max_len)
        self._prefills: Dict[int, Any] = {}
        self.prefill_chunk = int(prefill_chunk)
        self.prefix_pages = int(prefix_pages)
        self.page_len = int(page_len)
        # the v2 program families + the device page pool — pool buffers
        # use the SAME layer keying as the cache so tree_map pairs them
        self.chunk_prefill = (make_chunk_prefill(model)
                              if self.prefill_chunk else None)
        self.sample_at = make_sample_at() if self.prefill_chunk else None
        if self.prefix_pages:
            self.prefix_pool = init_cache(
                model, self.prefix_pages, self.page_len, cache_dtype)
            self.map_page, self.publish_page = make_page_copy(
                self.page_len)
        else:
            self.prefix_pool = None
        self._jnp = jnp

    def prefill_for(self, bucket: int):
        fn = self._prefills.get(bucket)
        if fn is None:
            fn = self._prefills[bucket] = make_prefill(
                self.model, bucket, self.cache_dtype)
        return fn

    def warm(self, buckets: Optional[List[int]] = None) -> None:
        """Compile the decode step, the insert, and the given prefill
        buckets on dummy data — the hot-swap contract: every program a
        request can hit is compiled BEFORE traffic switches.  With
        serve-v2 features on, the chunk program and the page-copy pair
        are part of that surface."""
        import jax
        import jax.numpy as jnp

        B = self.n_slots
        zero = jnp.zeros((), jnp.float32)
        key = jax.random.PRNGKey(0)
        tok, rngs, cache = self.decode(
            self.params, self.cache, jnp.zeros((B,), jnp.int32),
            jnp.zeros((B,), jnp.int32), jnp.stack([key] * B),
            jnp.zeros((B,), jnp.float32), jnp.zeros((B,), jnp.int32),
            jnp.ones((B,), jnp.float32))
        jax.block_until_ready(tok)
        for b in (buckets if buckets is not None else self.buckets[:1]):
            fn = self.prefill_for(b)
            t, _, small = fn(self.params, jnp.zeros((1, b), jnp.int32),
                             jnp.asarray(1), key, zero,
                             jnp.asarray(0, jnp.int32),
                             jnp.asarray(1.0, jnp.float32))
            jax.block_until_ready(
                self.insert(cache, small, jnp.asarray(0, jnp.int32)))
        i0 = jnp.asarray(0, jnp.int32)
        if self.prefill_chunk:
            lg, c2 = self.chunk_prefill(
                self.params, self.cache,
                jnp.zeros((1, self.prefill_chunk), jnp.int32), i0, i0)
            t, _ = self.sample_at(lg, i0, key, zero, i0,
                                  jnp.asarray(1.0, jnp.float32))
            jax.block_until_ready(t)
        if self.prefix_pool is not None:
            pool2 = self.publish_page(self.prefix_pool, self.cache,
                                      i0, i0, i0)
            jax.block_until_ready(
                self.map_page(self.cache, pool2, i0, i0, i0))


class ServeEngine:
    """Continuous-batching serving over one model/params bundle (see
    module docstring).  Construction compiles nothing; the first
    admission/step does (or call ``programs.warm()`` up front)."""

    def __init__(self, model, params, *, n_slots: int = 4,
                 max_len: int = 256, cache_dtype=None, page_len: int = 0,
                 page_budget: int = 0, run_dir: Optional[str] = None,
                 checkpoint_meta: Optional[dict] = None,
                 retain_results: bool = True, queue_bound: int = 0,
                 prefix_pages: int = 0, prefill_chunk: int = 0,
                 prefill_token_cap: int = 0, qos=None):
        """``retain_results=False`` (the long-running HTTP server) stops
        the engine from accumulating completed Request objects — each
        request (and, across a hot-swap, the old checkpoint's program
        set its ``served_by`` pins) is released as soon as its waiter
        collects it, so memory stays bounded by in-flight work.  Batch
        front ends (synthetic/stdin) keep the default: they need the
        full result list for verification and percentile reporting.

        Serve v2 knobs (all default OFF): ``prefix_pages`` sizes the
        shared prefix-page pool (> 0 enables prefix sharing and, if
        ``prefill_chunk`` is unset, auto-picks a chunk width dividing
        both the page and the slot length); ``prefill_chunk`` enables
        chunked prefill; ``prefill_token_cap`` bounds prefill work per
        engine step (floored at one chunk so progress is guaranteed)."""
        import jax
        import jax.numpy as jnp

        if getattr(model, "input_dtype", None) != "int32":
            raise ValueError(
                "ServeEngine serves token-sequence (LM) models; "
                f"got input_dtype={getattr(model, 'input_dtype', None)!r}")
        cache_dtype = jnp.float32 if cache_dtype is None else cache_dtype
        allocator = KVCacheAllocator(
            n_slots, max_len, page_len=page_len,
            page_budget=page_budget, prefix_pages=prefix_pages)
        if prefix_pages and not prefill_chunk:
            # sharing REQUIRES chunking: mapped pages are only canonical
            # when every producer prefilled at the same global chunk
            # alignment — a whole-bucket prefill would break bit parity
            prefill_chunk = default_prefill_chunk(
                max_len, allocator.page_len)
        if prefill_chunk:
            if max_len % prefill_chunk or \
                    allocator.page_len % prefill_chunk:
                raise ValueError(
                    f"prefill_chunk {prefill_chunk} must divide both "
                    f"max_len {max_len} and page_len "
                    f"{allocator.page_len} (in-bounds chunk writes + "
                    f"global chunk alignment)")
        self.prefill_chunk = int(prefill_chunk)
        self.prefill_token_cap = int(prefill_token_cap)
        self._prefix_pages = int(prefix_pages)
        self.programs = _Programs(
            model, params, n_slots=n_slots, max_len=max_len,
            cache_dtype=cache_dtype, meta=checkpoint_meta,
            page_len=allocator.page_len, prefix_pages=prefix_pages,
            prefill_chunk=self.prefill_chunk)
        # whether the decode step runs the decode-shaped Pallas kernel
        # (ops/decode_attention.py) at this cache geometry — surfaced as
        # a gauge so obs report / bench rows name the attention path
        from torchpruner_tpu.generate import _attn_layers
        from torchpruner_tpu.ops import decode_attention as _da

        head_dim = next((int(spec.head_dim)
                         for _, spec in _attn_layers(model.layers)), 0)
        self.decode_kernel = bool(
            head_dim and _da.kernel_active(max_len, head_dim, cache_dtype))
        obs.gauge_set(
            "serve_decode_kernel_active", float(self.decode_kernel),
            help="1 when the decode-shaped Pallas attention kernel "
                 "serves this engine's cache geometry")
        # static cost model: predict the slot-decode step at THIS
        # engine's geometry (slots × max_len × cache dtype) so the
        # serve run's report.json carries predicted_step_ms_decode /
        # predicted_comm_ms_decode next to the measured per-token
        # latency (obs diff renders the drift).  Best-effort and
        # param-budgeted; TORCHPRUNER_COST_PREDICT=0 opts out.  The
        # twin compile is deferred to the first step() — construction
        # compiles NOTHING (the hot-swap overlap window relies on it).
        self._cost_predicted = False
        self._cost_thread: Optional[threading.Thread] = None
        self.scheduler = Scheduler(
            allocator, queue_bound=queue_bound,
            prefill_token_cap=prefill_token_cap, qos=qos)
        # preemption (qos) may only reclaim slots whose prefill is NOT
        # mid-flight: a preempted slot mid-chunked-prefill would leave
        # _prefilling state pointing at an evicted request
        self.scheduler.preempt_guard = self._slot_preemptible
        self.run_dir = run_dir
        self.n_slots, self.max_len = n_slots, max_len
        # host slot tables (the continuous-batching state the compiled
        # step is parameterized by)
        self._pos = np.zeros(n_slots, np.int32)
        self._tok = np.zeros(n_slots, np.int32)
        self._temp = np.zeros(n_slots, np.float32)
        self._topk = np.zeros(n_slots, np.int32)
        self._topp = np.ones(n_slots, np.float32)
        self._last_token_s = np.zeros(n_slots, np.float64)
        self._rngs = jnp.stack([jax.random.PRNGKey(0)] * n_slots)
        self.steps = 0
        #: loop-iteration clock — unlike ``steps`` it advances even when
        #: the slot array is idle, so a step-indexed open-loop schedule
        #: can never stall waiting for a decode that will never happen
        self.ticks = 0
        self.gen_tokens = 0
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None
        self._pending_swap: Optional[str] = None
        self._staged: Optional[_Programs] = None
        self._swap_error: Optional[BaseException] = None
        self._swap_thread: Optional[threading.Thread] = None
        self.swaps_total = 0
        #: slot -> in-progress chunked-prefill state (insertion order =
        #: round-robin order; a slot mid-prefill is skipped by decode
        #: harvesting and its decode write row is parked)
        self._prefilling: Dict[int, dict] = {}
        #: lifetime prefill-token work actually computed (chunk real
        #: tokens / legacy bucket prompt lengths) — the sharing-on/off
        #: "prefilled tokens drop >= 2x" comparison reads this
        self.prefill_tokens_total = 0
        #: the largest per-step prefill-token spend observed — the
        #: "no step exceeds the cap" bench gate
        self.max_prefill_tokens_step = 0
        self.drained: List[Request] = []
        self.retain_results = retain_results
        self.completed_count = 0
        self._results: List[Request] = []
        #: gen-token count at the start of the current run() window —
        #: summary()'s throughput covers the LAST run, not the engine's
        #: lifetime (a warmup pass must not dilute the measured phase)
        self._window_tokens0 = 0
        self._eos = np.full(n_slots, -1, np.int64)
        #: optional live SLO monitor (serve.slo.SLOMonitor) — fed TTFT /
        #: per-token observations and checked at step boundaries; the
        #: property setter also wires the scheduler's queue-age hook so
        #: queue waits join the burn-rate evaluation
        self.slo = None
        #: the preemption handler of the CURRENT run() — lets
        #: health_state() report "draining" the instant a SIGTERM lands,
        #: before the loop reaches its next boundary
        self._preemption = None

    @property
    def slo(self):
        return self._slo

    @slo.setter
    def slo(self, monitor):
        # drivers assign `engine.slo = SLOMonitor(...)` directly; the
        # setter keeps the scheduler's queue-age hook in sync so the
        # monitor sees admission waits without the scheduler knowing
        # the monitor's type
        self._slo = monitor
        self.scheduler.on_queue_wait = (
            monitor.on_queue if monitor is not None else None)

    # -- submission ---------------------------------------------------------

    @property
    def model(self):
        return self.programs.model

    @property
    def params(self):
        return self.programs.params

    def submit(self, request: Request,
               arrival_s: Optional[float] = None) -> Request:
        if request.total_len > self.max_len:
            raise ValueError(
                f"request needs {request.total_len} cache positions "
                f"(prompt {request.prompt_ids.size} + max_new "
                f"{request.max_new}) > engine max_len {self.max_len}")
        request.sampling.validate(0)
        return self.scheduler.submit(request, arrival_s=arrival_s)

    # -- the step-boundary machine -----------------------------------------

    def _slot_preemptible(self, slot: int) -> bool:
        return slot not in self._prefilling

    def _prefill(self, req: Request) -> None:
        import jax
        import jax.numpy as jnp

        P = self.programs
        slot = req.slot
        n = int(req.prompt_ids.size)
        bucket = bucket_for(n, P.buckets)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = req.prompt_ids
        s = req.sampling
        t_adm = time.perf_counter()
        if req.admitted_s is not None:
            # admission stage: slot granted -> this request's prefill
            # actually starting (a batch admission serializes prefills,
            # so later batch members wait here)
            reqtrace.stage(req.trace_id, "admission",
                           dur_s=max(0.0, t_adm - req.admitted_s),
                           request=req.id)
        with obs.span("serve_prefill", request=req.id, bucket=bucket):
            tok, carry, small = P.prefill_for(bucket)(
                P.params, jnp.asarray(padded), jnp.asarray(n),
                jax.random.PRNGKey(s.seed),
                jnp.asarray(s.temperature, jnp.float32),
                jnp.asarray(s.top_k or 0, jnp.int32),
                jnp.asarray(1.0 if s.top_p is None else s.top_p,
                            jnp.float32))
            P.cache = P.insert(P.cache, small,
                               jnp.asarray(slot, jnp.int32))
            tok = int(tok)
        now = time.perf_counter()
        req.first_token_s = now
        req.prefill_s = now - t_adm
        req.served_by = P  # which checkpoint's programs decoded it
        req.tokens.append(tok)
        self.gen_tokens += 1
        req.prefilled_tokens = n
        self.prefill_tokens_total += n
        obs.inc("serve_prefill_tokens_total", n=n,
                help="prompt tokens actually prefilled (chunked real "
                     "tokens or whole-bucket prompt lengths; prefix "
                     "hits skip theirs)")
        reqtrace.stage(req.trace_id, "prefill", dur_s=req.prefill_s,
                       request=req.id, bucket=bucket)
        if req.ttft_s is not None:
            obs.observe("serve_ttft_seconds", req.ttft_s,
                        help="request arrival -> first token")
            reqtrace.stage(req.trace_id, "first_token", request=req.id,
                           ttft_s=round(req.ttft_s, 6))
            if self.slo is not None:
                self.slo.on_ttft(req.ttft_s)
        # slot tables: next write position is the prompt length
        self._pos[slot] = n
        self._tok[slot] = tok
        self._temp[slot] = s.temperature
        self._topk[slot] = s.top_k or 0
        self._topp[slot] = 1.0 if s.top_p is None else s.top_p
        self._eos[slot] = -1 if req.eos_id is None else req.eos_id
        self._last_token_s[slot] = now
        self._rngs = self._rngs.at[slot].set(carry)
        if len(req.tokens) >= req.max_new or tok == self._eos[slot]:
            self._finish(req)

    # -- serve v2: prefix sharing + chunked prefill --------------------------

    def _begin_prefill(self, req: Request) -> None:
        """Admission under chunked prefill: match + map the prompt's
        shared prefix pages (pinning the trie path), then enqueue the
        suffix for chunk-by-chunk prefilling interleaved with decode
        steps.  The match is capped at ``prompt_len - 1`` so at least
        one real position is always computed (the first token's logits
        live there)."""
        import jax.numpy as jnp

        P = self.programs
        alloc = self.scheduler.allocator
        slot = req.slot
        n = int(req.prompt_ids.size)
        t_adm = time.perf_counter()
        if req.admitted_s is not None:
            reqtrace.stage(req.trace_id, "admission",
                           dur_s=max(0.0, t_adm - req.admitted_s),
                           request=req.id)
        pos0 = 0
        match = alloc.match_prefix(req.prompt_ids, max_tokens=n - 1)
        if match is not None:
            Lp = alloc.page_len
            with obs.span("serve_prefix_map", request=req.id,
                          pages=len(match.pages)):
                for i, pg in enumerate(match.pages):
                    P.cache = P.map_page(
                        P.cache, P.prefix_pool,
                        jnp.asarray(pg, jnp.int32),
                        jnp.asarray(slot, jnp.int32),
                        jnp.asarray(i * Lp, jnp.int32))
            pos0 = match.tokens
            alloc.lease_of(slot).prefix_match = match
            req.prefix_hit_tokens = pos0
            obs.inc("serve_prefix_hits_total",
                    help="admissions whose prompt matched resident "
                         "prefix pages")
            obs.inc("serve_prefix_hit_tokens_total", n=pos0,
                    help="prompt tokens served by mapping shared "
                         "prefix pages instead of re-prefilling")
            # pages the trie held but the cap refused (they straddle
            # the sampled position / future decode writes): the
            # copy-on-write boundary, privately re-prefilled
            cow = -(-(min(getattr(match, "available", pos0), n)
                      - pos0) // Lp)
            if cow > 0:
                obs.inc("serve_prefix_cow_pages_total", n=cow,
                        help="resident pages re-prefilled privately at "
                             "the divergence/write boundary (COW)")
            reqtrace.stage(req.trace_id, "prefix_hit", request=req.id,
                           tokens=pos0)
        elif alloc.prefix_enabled:
            obs.inc("serve_prefix_misses_total",
                    help="admissions with no resident prefix page")
        # park the slot's decode write row on max_len - 1: that row is
        # never attendable before decode overwrites it (the final
        # decode step's pos is at most total_len - 2), so the junk
        # writes of interleaved decode steps cannot corrupt this
        # prefill — and this slot's step outputs are discarded
        self._pos[slot] = self.max_len - 1
        self._tok[slot] = 0
        self._temp[slot] = 0.0
        self._topk[slot] = 0
        self._topp[slot] = 1.0
        self._prefilling[slot] = {
            "req": req, "pos": pos0, "start": pos0, "t0": t_adm}

    def _advance_prefills(self) -> bool:
        """One engine step's prefill work: round-robin one chunk per
        mid-prefill slot while the scheduler's per-step token budget
        lasts.  Chunk work (padded width) is what the budget meters —
        the conservative reading of the cap."""
        chunk = self.prefill_chunk
        budget = self.scheduler.prefill_budget(chunk)
        spent = 0
        progressed = False
        for slot in list(self._prefilling):
            if spent + chunk > budget:
                break
            st = self._prefilling.get(slot)
            if st is None:
                continue
            self._prefill_one_chunk(slot, st)
            spent += chunk
            progressed = True
            if slot in self._prefilling:  # not finished: rotate to back
                self._prefilling[slot] = self._prefilling.pop(slot)
        if spent:
            self.max_prefill_tokens_step = max(
                self.max_prefill_tokens_step, spent)
        return progressed

    def _prefill_one_chunk(self, slot: int, st: dict) -> None:
        import jax.numpy as jnp

        P = self.programs
        req: Request = st["req"]
        n = int(req.prompt_ids.size)
        c = self.prefill_chunk
        pos = st["pos"]
        m = min(c, n - pos)
        toks = np.zeros((1, c), np.int32)
        toks[0, :m] = req.prompt_ids[pos:pos + m]
        with obs.span("serve_prefill_chunk", request=req.id, chunk=c):
            logits, P.cache = P.chunk_prefill(
                P.params, P.cache, jnp.asarray(toks),
                jnp.asarray(slot, jnp.int32),
                jnp.asarray(pos, jnp.int32))
        st["pos"] = pos + m
        self.prefill_tokens_total += m
        obs.inc("serve_prefill_tokens_total", n=m,
                help="prompt tokens actually prefilled (chunked real "
                     "tokens or whole-bucket prompt lengths; prefix "
                     "hits skip theirs)")
        obs.inc("serve_prefill_chunks_total",
                help="chunk-prefill program applications")
        if st["pos"] >= n:
            self._finish_prefill(slot, st, logits, pos)

    def _finish_prefill(self, slot: int, st: dict, logits,
                        last_chunk_pos: int) -> None:
        import jax
        import jax.numpy as jnp

        P = self.programs
        alloc = self.scheduler.allocator
        req: Request = st["req"]
        s = req.sampling
        n = int(req.prompt_ids.size)
        tok, carry = P.sample_at(
            logits, jnp.asarray(n - 1 - last_chunk_pos, jnp.int32),
            jax.random.PRNGKey(s.seed),
            jnp.asarray(s.temperature, jnp.float32),
            jnp.asarray(s.top_k or 0, jnp.int32),
            jnp.asarray(1.0 if s.top_p is None else s.top_p,
                        jnp.float32))
        tok = int(tok)
        now = time.perf_counter()
        req.first_token_s = now
        req.prefill_s = now - st["t0"]
        req.served_by = P
        req.tokens.append(tok)
        self.gen_tokens += 1
        req.prefilled_tokens = n - st["start"]
        reqtrace.stage(req.trace_id, "prefill", dur_s=req.prefill_s,
                       request=req.id, chunk=self.prefill_chunk,
                       hit_tokens=st["start"])
        if req.ttft_s is not None:
            obs.observe("serve_ttft_seconds", req.ttft_s,
                        help="request arrival -> first token")
            reqtrace.stage(req.trace_id, "first_token", request=req.id,
                           ttft_s=round(req.ttft_s, 6))
            if self.slo is not None:
                self.slo.on_ttft(req.ttft_s)
        self._pos[slot] = n
        self._tok[slot] = tok
        self._temp[slot] = s.temperature
        self._topk[slot] = s.top_k or 0
        self._topp[slot] = 1.0 if s.top_p is None else s.top_p
        self._eos[slot] = -1 if req.eos_id is None else req.eos_id
        self._last_token_s[slot] = now
        self._rngs = self._rngs.at[slot].set(carry)
        if alloc.prefix_enabled:
            ev0, full0 = alloc.prefix_evictions, \
                alloc.prefix_pool_exhausted
            plan = alloc.publish_prefix(req.prompt_ids, n)
            if plan:
                Lp = alloc.page_len
                with obs.span("serve_prefix_publish", request=req.id,
                              pages=len(plan)):
                    for pi, pg in plan:
                        P.prefix_pool = P.publish_page(
                            P.prefix_pool, P.cache,
                            jnp.asarray(slot, jnp.int32),
                            jnp.asarray(pi * Lp, jnp.int32),
                            jnp.asarray(pg, jnp.int32))
                obs.inc("serve_prefix_published_pages_total",
                        n=len(plan),
                        help="whole prompt pages published into the "
                             "shared pool")
            if alloc.prefix_evictions > ev0:
                obs.inc("serve_prefix_evicted_pages_total",
                        n=alloc.prefix_evictions - ev0,
                        help="pool pages reclaimed by refcount-aware "
                             "LRU eviction")
            if alloc.prefix_pool_exhausted > full0:
                obs.inc("serve_prefix_pool_exhausted_total",
                        n=alloc.prefix_pool_exhausted - full0,
                        help="publications truncated with every pool "
                             "page pinned (evict-while-shared refusal)")
        del self._prefilling[slot]
        if len(req.tokens) >= req.max_new or tok == self._eos[slot]:
            self._finish(req)

    def _finish(self, req: Request) -> None:
        self.completed_count += 1
        if self.retain_results:
            self._results.append(req)
        self.scheduler.evict(req, state=DONE)
        if req.tenant:
            # per-tenant SLO breakdown inputs (obs report groups the
            # tenant_* scalars into one table per tenant)
            obs.inc(f"tenant_{req.tenant}_completed_total",
                    help="this tenant's completed requests")
            if req.ttft_s is not None:
                obs.observe(f"tenant_{req.tenant}_ttft_seconds",
                            req.ttft_s,
                            help="this tenant's arrival -> first token")
        if req.first_token_s is not None and req.done_s is not None:
            # unconditional like the other stages: an untraced serve
            # run's latency budget still needs the decode aggregate
            reqtrace.stage(req.trace_id, "decode",
                           dur_s=max(0.0,
                                     req.done_s - req.first_token_s),
                           request=req.id, tokens=len(req.tokens))
        if req.trace_id:
            reqtrace.stage(req.trace_id, "complete", request=req.id)
            e2e = (req.done_s - req.arrival_s
                   if req.done_s is not None and req.arrival_s is not None
                   else None)
            reqtrace.finish(
                req.trace_id, outcome="complete",
                ttft_s=(round(req.ttft_s, 6)
                        if req.ttft_s is not None else None),
                # the replica's local e2e (submit -> done): the sampled
                # recorder's slowest-K rank key — without it a sampled
                # replica would never flush its slow exemplars
                e2e_s=(round(e2e, 6) if e2e is not None else None),
                tokens=len(req.tokens))

    def _decode_once(self) -> None:
        import jax.numpy as jnp

        if _chaos.active():
            _chaos.maybe_slow_step()  # "slow replica" fleet fault
        P = self.programs
        t0 = time.perf_counter()
        # inactive slots decode junk under a clamped position; their
        # results are discarded and their cache rows are stale-safe
        pos = np.minimum(self._pos, self.max_len - 1)
        nxt, self._rngs, P.cache = P.decode(
            P.params, P.cache, jnp.asarray(self._tok), jnp.asarray(pos),
            self._rngs, jnp.asarray(self._temp), jnp.asarray(self._topk),
            jnp.asarray(self._topp))
        nxt = np.asarray(nxt)
        now = time.perf_counter()
        self.steps += 1
        obs.inc("serve_decode_steps_total",
                help="batched continuous-batching decode steps")
        # capture-cadence hook only — decode steps stay out of the
        # train step telemetry (obs.profile)
        obs.profile_step(now - t0)
        for slot, req in list(self.scheduler.running.items()):
            if slot in self._prefilling:
                # mid-chunked-prefill: this slot decoded junk at its
                # parked position — discard
                continue
            tok = int(nxt[slot])
            req.tokens.append(tok)
            self.gen_tokens += 1
            gap = now - self._last_token_s[slot]
            req.token_gaps_s.append(gap)
            obs.observe("serve_token_seconds", gap,
                        help="per-token latency (gap between a "
                             "request's successive tokens)")
            if self.slo is not None:
                self.slo.on_token(gap)
            self._last_token_s[slot] = now
            self._pos[slot] += 1
            self._tok[slot] = tok
            if len(req.tokens) >= req.max_new or tok == self._eos[slot]:
                self._finish(req)

    def step(self, admit: bool = True) -> bool:
        """One engine iteration: (boundary) admit + prefill, then one
        batched decode step.  Returns whether any work happened."""
        if self._t_first is None:
            self._t_first = time.perf_counter()
        if not self._cost_predicted:
            # the cost-model twin compiles on a BACKGROUND thread,
            # overlapping the first step's real decode/prefill compiles
            # instead of serializing after them (construction still
            # compiles nothing); summary() joins it before the gauges
            # are read out
            self._cost_predicted = True
            from torchpruner_tpu.analysis import cost_model

            self._cost_thread = threading.Thread(
                target=cost_model.record_decode_prediction,
                args=(self.programs.model,),
                kwargs=dict(n_slots=self.n_slots, max_len=self.max_len,
                            cache_dtype=self.programs.cache_dtype),
                daemon=True)
            self._cost_thread.start()
        did = False
        if admit:
            for req in self.scheduler.admit():
                if self.prefill_chunk:
                    self._begin_prefill(req)
                else:
                    self._prefill(req)
                did = True
        if self._prefilling:
            did = self._advance_prefills() or did
        if any(s not in self._prefilling
               for s in self.scheduler.running):
            self._decode_once()
            did = True
        if did:
            self._t_last = time.perf_counter()
        return did

    # -- hot-swap -----------------------------------------------------------

    def request_swap(self, checkpoint_dir: str) -> None:
        """Stage a freshly-pruned checkpoint for a step-boundary swap
        (see module docstring).  Restore + compile + warm run on a
        background thread so in-flight decoding never stalls; the
        switch itself happens inside :meth:`run` (or via
        :meth:`maybe_swap` between manual :meth:`step` calls)."""
        if self._pending_swap is not None:
            raise RuntimeError(
                f"a swap to {self._pending_swap!r} is already staging")
        self._pending_swap = checkpoint_dir
        # snapshot the exercised prefill buckets ON THIS THREAD: the
        # engine loop keeps admitting (and may insert new buckets)
        # while the staging thread runs — iterating the live dict there
        # would race
        buckets = sorted(self.programs._prefills)
        self._swap_thread = threading.Thread(
            target=self._stage_swap, args=(checkpoint_dir, buckets),
            daemon=True)
        self._swap_thread.start()

    def _stage_swap(self, path: str, buckets: List[int]) -> None:
        """Background staging: every program a request can hit is
        compiled BEFORE traffic switches — the decode step + the prompt
        buckets traffic had already exercised at stage time."""
        try:
            from torchpruner_tpu.checkpoint import restore_checkpoint

            with obs.span("serve_swap_compile", checkpoint=path):
                model, params, _state, _opt, meta = \
                    restore_checkpoint(path)
                staged = _Programs(
                    model, params, n_slots=self.n_slots,
                    max_len=self.max_len,
                    cache_dtype=self.programs.cache_dtype,
                    meta={**(meta or {}), "checkpoint": path},
                    page_len=self.scheduler.allocator.page_len,
                    prefix_pages=self._prefix_pages,
                    prefill_chunk=self.prefill_chunk)
                staged.warm(buckets or None)
            self._staged = staged
        except Exception as e:  # surfaced at the next step boundary
            self._swap_error = e
            self._pending_swap = None

    def maybe_swap(self) -> bool:
        """Advance the swap state machine at a step boundary: report a
        failed staging, or switch once the staged programs are ready
        AND the slot array is empty.  Returns True when the switch
        happened this call."""
        if self._swap_error is not None:
            err, self._swap_error = self._swap_error, None
            obs.inc("serve_swap_errors_total",
                    help="hot-swap stagings that failed (bad/corrupt "
                         "checkpoint); serving continues on the old one")
            print(f"[serve] hot-swap failed, keeping current "
                  f"checkpoint: {type(err).__name__}: {err}",
                  file=sys.stderr, flush=True)
        if self._staged is not None and not self.scheduler.running:
            old, new = self.programs, self._staged
            self.programs = new
            self._staged, self._pending_swap = None, None
            self.swaps_total += 1
            # pooled prefix K/V was computed under the OLD weights —
            # a post-swap match would map stale pages; drop the index
            # (the slot array is empty here, so nothing is pinned)
            self.scheduler.allocator.reset_prefix()
            obs.inc("serve_swaps_total",
                    help="checkpoint hot-swaps completed")
            obs.record_serve(
                kind="hot_swap",
                old_digest=(old.meta or {}).get("digest"),
                new_digest=(new.meta or {}).get("digest"),
                checkpoint=(new.meta or {}).get("checkpoint"),
                widths=new.model.widths(), at_step=self.steps)
            return True
        return False

    # -- health -------------------------------------------------------------

    def health_state(self) -> str:
        """Readiness, distinct from liveness (the process answering at
        all): ``ready`` | ``draining`` (SIGTERM landed / drain begun —
        submissions bounce, stop dispatching here) | ``staging_swap``
        (a checkpoint swap is staging; admissions pause once it's
        warm) | ``slo_breach`` (a rolling p99 is over its threshold —
        prefer other replicas).  The ``/healthz`` endpoint maps
        non-``ready`` states to 503, the k8s-style readiness-probe
        contract the fleet router keys off."""
        if self.scheduler.closed or (
                self._preemption is not None
                and self._preemption.requested):
            return "draining"
        if self._pending_swap is not None:
            return "staging_swap"
        if self.slo is not None and self.slo.in_breach_any():
            return "slo_breach"
        return "ready"

    # -- drain / loop -------------------------------------------------------

    def _snapshot_queue(self, extra: Optional[List[Request]] = None) -> None:
        self.scheduler.close()  # later submissions bounce
        queued = self.scheduler.drain_queue() + list(extra or [])
        for req in queued:
            req.state = DRAINED
            req._event.set()
            if req.trace_id:
                reqtrace.finish(req.trace_id, outcome="drained")
        self.drained.extend(queued)
        if queued:
            obs.inc("serve_drained_total", n=len(queued),
                    help="queued requests snapshotted at drain")
        if self.run_dir:
            import os

            from torchpruner_tpu.resilience.manifest import (
                atomic_write_json,
            )

            os.makedirs(self.run_dir, exist_ok=True)
            atomic_write_json(
                os.path.join(self.run_dir, SNAPSHOT_FILENAME),
                {"drained_at": time.time(),
                 "requests": [r.snapshot() for r in queued]})

    def run(self, traffic=None, *, preemption=None,
            max_steps: Optional[int] = None, stop_event=None,
            idle_wait_s: float = 5e-4,
            stop_when_drained: bool = True) -> dict:
        """The engine loop: pump open-loop traffic, honor preemption
        (drain in-flight, snapshot the queue, exit cleanly), advance
        the hot-swap state machine, and step.  Returns
        :meth:`summary` (whose throughput window covers THIS run)."""
        # fresh throughput window: a prior warmup/calibration run must
        # not dilute this run's sustained tok/s
        self._t_first = None
        self._t_last = None
        self._window_tokens0 = self.gen_tokens
        self._preemption = preemption
        draining = False
        while True:
            self.ticks += 1
            if traffic is not None and not draining:
                traffic.pump(self)
            want_stop = (
                (preemption is not None and preemption.requested)
                or (stop_event is not None and stop_event.is_set()))
            if want_stop and not draining:
                draining = True
                # everything not yet in flight — queued requests AND the
                # traffic generator's not-yet-submitted arrivals — goes
                # into the resubmission snapshot; only in-flight work
                # keeps running
                extra = traffic.drain() if traffic is not None and \
                    hasattr(traffic, "drain") else []
                self._snapshot_queue(extra)
            if not draining:
                self.maybe_swap()
            # admissions keep flowing while a swap STAGES on its thread;
            # they stop only once the staged programs are ready (the
            # drain-then-switch boundary)
            did = self.step(admit=not draining and self._staged is None)
            if self.slo is not None:
                self.slo.maybe_check(self.steps)
            # on-demand profiler windows (POST /profile) must open/close
            # even when the slot array sits idle between requests
            obs.profile_tick()
            # windowed time-series: the run loop is the engine's clock
            # (decode steps stall while idle, windows must not)
            obs.timeseries_tick()
            if max_steps is not None and self.steps >= max_steps:
                break
            if not self.scheduler.has_work():
                if draining:
                    break
                if self._pending_swap is not None:
                    # a staged/staging swap is outstanding work: stay
                    # alive so it can land (maybe_swap switches on the
                    # next iteration once the thread finishes)
                    time.sleep(idle_wait_s)
                    continue
                if traffic is not None and traffic.exhausted:
                    break
                if traffic is None and stop_event is None \
                        and stop_when_drained:
                    break
                if not did:
                    time.sleep(idle_wait_s)
        if draining:
            obs.inc("serve_preempt_drains_total",
                    help="preemption drains completed")
        return self.summary()

    # -- reporting ----------------------------------------------------------

    def results(self) -> List[Request]:
        return list(self._results)

    def summary(self) -> dict:
        """Headline serving stats; also pushes the sustained-throughput
        gauge and the serve ledger record so ``obs report`` can render
        the run.  Counts (requests/admits/evictions/swaps) are engine
        LIFETIME; the throughput window (``gen_tokens`` / ``wall_s`` /
        ``sustained_gen_tok_s``) covers the most recent :meth:`run`;
        latency percentiles come from retained results (``None`` with
        ``retain_results=False`` — read the obs histograms instead)."""
        if self._cost_thread is not None:
            # bound the wait: a wedged twin compile must not hang the
            # summary — the gauges just stay absent (best-effort)
            self._cost_thread.join(timeout=120.0)
            self._cost_thread = None
        done = [r for r in self._results if r.state == DONE]
        wall = ((self._t_last - self._t_first)
                if self._t_first is not None and self._t_last is not None
                else 0.0)
        window_tokens = self.gen_tokens - self._window_tokens0
        ttfts = [r.ttft_s for r in done if r.ttft_s is not None]
        gaps = [g for r in done for g in r.token_gaps_s]

        def pct(xs, q):
            return round(float(np.percentile(xs, q)) * 1e3, 3) \
                if xs else None

        out = {
            "requests_completed": self.completed_count,
            "requests_drained": len(self.drained),
            "decode_steps": self.steps,
            "gen_tokens": window_tokens,
            "wall_s": round(wall, 4),
            "sustained_gen_tok_s": (round(window_tokens / wall, 1)
                                    if wall > 0 else None),
            "ttft_p50_ms": pct(ttfts, 50),
            "ttft_p99_ms": pct(ttfts, 99),
            "token_p50_ms": pct(gaps, 50),
            "token_p99_ms": pct(gaps, 99),
            "admits": self.scheduler.admitted_total,
            "evictions": self.scheduler.allocator.total_evictions,
            "swaps": self.swaps_total,
            "decode_kernel": self.decode_kernel,
        }
        out["prefilled_tokens"] = self.prefill_tokens_total
        if self.prefill_chunk:
            out["prefill_chunk"] = self.prefill_chunk
            out["max_prefill_tokens_step"] = self.max_prefill_tokens_step
            out["prefill_token_cap"] = (
                self.scheduler.prefill_budget(self.prefill_chunk)
                if self.prefill_token_cap else 0)
        alloc = self.scheduler.allocator
        if alloc.prefix_enabled:
            hit, computed = alloc.prefix_hit_tokens, \
                self.prefill_tokens_total
            out.update({
                "prefix_hits": alloc.prefix_hits,
                "prefix_misses": alloc.prefix_misses,
                "prefix_hit_tokens": hit,
                # fraction of prompt tokens served from the pool
                "prefix_hit_rate": round(hit / (hit + computed), 4)
                if hit + computed else 0.0,
                "prefix_pool_pages": alloc.prefix_pages,
                "prefix_evictions": alloc.prefix_evictions,
            })
        if out["sustained_gen_tok_s"] is not None:
            obs.gauge_set("serve_gen_tokens_per_s",
                          out["sustained_gen_tok_s"],
                          help="sustained generated tokens per second")
        obs.record_serve(
            kind="summary",
            checkpoint_digest=self.programs.meta.get("digest"), **out)
        return out

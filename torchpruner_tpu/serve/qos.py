"""Multi-tenant QoS primitives for the serving plane.

A **tenant** is a traffic class sharing one engine/fleet: "interactive"
chat traffic, "batch" offline jobs, a named customer — anything whose
overload must not starve the others.  Policy is three orthogonal knobs
per tenant (:class:`TenantPolicy`):

- **priority class** — admission order at the step boundary.  Lower
  numbers admit first; an interactive head-of-queue may PREEMPT an
  active lower-priority request (scheduler.admit, and only there — the
  engine already confines every slot-table mutation to step
  boundaries, so "interactive preempts batch strictly at step
  boundaries" is structural, not a timing promise).
- **token bucket** — submission-rate throttling (:class:`TokenBucket`):
  ``rate`` requests/s sustained with ``burst`` headroom.  An empty
  bucket SHEDS at submit (``serve_rejected_throttle_total``) with the
  same 503 + Retry-After contract as the queue bound, so one tenant's
  flood never occupies queue slots the others need.
- **KV-page quota** — a ceiling on the tenant's simultaneous KV-cache
  pages (enforced in the allocator): an over-quota admission is shed
  (``serve_rejected_quota_total``) instead of blocking the FIFO head,
  so a long-context tenant cannot squat the whole page budget.

Buckets use an injected monotonic clock (``now``) so refill/burst math
is unit-testable without sleeping; in production callers pass nothing
and get ``time.monotonic()``.
"""

from __future__ import annotations

import re
import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional

#: canonical priority classes (lower admits first)
INTERACTIVE = 0
BATCH = 1

_PRIORITY_NAMES = {"interactive": INTERACTIVE, "batch": BATCH}
#: tenant names become obs scalar segments (``tenant_<name>_*``) — keep
#: them parseable
_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")


@dataclass(frozen=True)
class TenantPolicy:
    """One tenant's QoS contract.  ``rate == 0`` disables throttling;
    ``page_quota == 0`` disables the KV quota; ``priority`` defaults to
    interactive (the unthrottled default tenant behaves exactly like
    the pre-QoS scheduler)."""

    name: str
    priority: int = INTERACTIVE
    #: sustained submissions/s through the token bucket (0 = unlimited)
    rate: float = 0.0
    #: bucket capacity — how far above ``rate`` a burst may spike
    burst: float = 1.0
    #: max simultaneous KV-cache pages leased to this tenant (0 = none)
    page_quota: int = 0
    #: preemptible: an active request of this tenant may be evicted at
    #: a step boundary to admit a higher-priority head-of-queue
    preemptible: bool = False

    def __post_init__(self):
        if not _NAME_RE.match(self.name):
            raise ValueError(
                f"tenant name {self.name!r} must match {_NAME_RE.pattern}"
                " (it becomes an obs scalar segment)")
        if self.rate < 0 or self.burst < 0:
            raise ValueError(f"rate/burst must be >= 0 for {self.name!r}")

    @classmethod
    def from_dict(cls, name: str, d: dict) -> "TenantPolicy":
        """Parse the wire/scenario form: ``{"priority": "batch"|int,
        "rate": 5.0, "burst": 10, "page_quota": 8, "preemptible":
        true}`` — unknown keys rejected (config-typo guard, the
        ``FleetChaos.from_any`` discipline)."""
        unknown = set(d) - {"priority", "rate", "burst", "page_quota",
                            "preemptible"}
        if unknown:
            raise ValueError(f"unknown tenant policy key(s) for "
                             f"{name!r}: {sorted(unknown)}")
        prio = d.get("priority", INTERACTIVE)
        if isinstance(prio, str):
            if prio not in _PRIORITY_NAMES:
                raise ValueError(f"unknown priority class {prio!r} "
                                 f"(want {sorted(_PRIORITY_NAMES)})")
            prio = _PRIORITY_NAMES[prio]
        return cls(name=name, priority=int(prio),
                   rate=float(d.get("rate", 0.0)),
                   burst=float(d.get("burst", 1.0)),
                   page_quota=int(d.get("page_quota", 0)),
                   preemptible=bool(d.get("preemptible",
                                          int(prio) > INTERACTIVE)))


class TokenBucket:
    """Classic leaky-bucket admission meter.  ``level`` refills at
    ``rate`` tokens/s up to ``burst``; :meth:`take` spends one token or
    answers how long until one is available.  Thread-safe: frontends
    submit from handler threads while the engine loop runs."""

    def __init__(self, rate: float, burst: float = 1.0,
                 now: Optional[float] = None):
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self._level = self.burst
        self._t = time.monotonic() if now is None else float(now)
        self._lock = threading.Lock()

    def _refill_locked(self, now: float) -> None:
        dt = max(0.0, now - self._t)
        self._t = now
        self._level = min(self.burst, self._level + dt * self.rate)

    def take(self, now: Optional[float] = None) -> bool:
        """Spend one token; ``False`` = throttled (shed)."""
        if self.rate <= 0:
            return True
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            self._refill_locked(now)
            if self._level >= 1.0:
                self._level -= 1.0
                return True
            return False

    def retry_after_s(self, now: Optional[float] = None) -> float:
        """Seconds until one token will be available — the Retry-After
        hint for a throttled submission."""
        if self.rate <= 0:
            return 0.0
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            self._refill_locked(now)
            if self._level >= 1.0:
                return 0.0
            return (1.0 - self._level) / self.rate

    @property
    def level(self) -> float:
        with self._lock:
            return self._level


class QoS:
    """Per-tenant policy table + live token buckets.  ``None`` tenants
    (every pre-QoS caller) get :attr:`default` — unthrottled,
    interactive, no quota — so a scheduler with an empty table behaves
    bit-for-bit like the FIFO it replaced."""

    def __init__(self, policies: Optional[Dict[str, TenantPolicy]] = None,
                 now: Optional[float] = None):
        self.policies: Dict[str, TenantPolicy] = dict(policies or {})
        self.default = TenantPolicy(name="default")
        self._buckets: Dict[str, TokenBucket] = {
            name: TokenBucket(p.rate, p.burst, now=now)
            for name, p in self.policies.items() if p.rate > 0}

    @classmethod
    def from_dict(cls, d: Optional[dict],
                  now: Optional[float] = None) -> "QoS":
        return cls({name: TenantPolicy.from_dict(name, cfg)
                    for name, cfg in (d or {}).items()}, now=now)

    def policy(self, tenant: Optional[str]) -> TenantPolicy:
        if tenant is None:
            return self.default
        return self.policies.get(tenant, self.default)

    def bucket(self, tenant: Optional[str]) -> Optional[TokenBucket]:
        return self._buckets.get(tenant) if tenant else None

    def admit_now(self, tenant: Optional[str],
                  now: Optional[float] = None) -> bool:
        """Token-bucket gate for one submission (True = pass)."""
        b = self.bucket(tenant)
        return True if b is None else b.take(now=now)

    @property
    def priorities(self):
        """Sorted distinct priority classes in the table (always
        includes the default class)."""
        out = {self.default.priority}
        out.update(p.priority for p in self.policies.values())
        return sorted(out)

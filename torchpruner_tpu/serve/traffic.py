"""Synthetic open-loop traffic for the serving engine.

Open-loop means arrivals follow their OWN schedule regardless of how
fast the engine drains them — the regime where queueing delay, TTFT
tails, and slot contention actually show up (a closed loop that waits
for each response can never overload the server).  Two schedules:

- **Poisson** (``poisson_arrivals``) — exponential inter-arrival gaps
  at a target rate, the classic serving-bench workload; wall-clock
  driven (bench ``serve`` leg).
- **Step-staggered** (``staggered_arrivals``) — arrivals pinned to
  ENGINE STEP indices, fully deterministic regardless of host speed;
  what CI uses to force mid-run admissions and slot reuse
  reproducibly.

Requests are seeded synthetics: prompt ids uniform over the model's
vocab, lengths/budgets drawn from ranges, per-request sampling seeds —
the same request replayed through ``generate`` solo reproduces its
tokens (the CI parity assertion).
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence

import numpy as np

from torchpruner_tpu.serve.request import Request, Sampling


def poisson_arrivals(n: int, rate_per_s: float, seed: int = 0) -> List[float]:
    """``n`` arrival offsets (seconds from traffic start) with
    exponential inter-arrival gaps at ``rate_per_s``."""
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / max(rate_per_s, 1e-9), size=n)
    return np.cumsum(gaps).tolist()


def staggered_arrivals(n: int, every_steps: int = 2,
                       burst: int = 1) -> List[int]:
    """Deterministic step-indexed arrivals: ``burst`` requests every
    ``every_steps`` engine steps (request 0 at step 0)."""
    return [(i // burst) * every_steps for i in range(n)]


def synthetic_requests(n: int, *, vocab: int, prompt_lens: Sequence[int],
                       max_new: Sequence[int], seed: int = 0,
                       temperature: float = 0.0,
                       eos_id: Optional[int] = None) -> List[Request]:
    """``n`` seeded synthetic requests.  ``prompt_lens`` / ``max_new``
    are cycled per request, so a mixed-length workload (different
    prefill buckets, different finish times — the ragged mix) is one
    list literal away."""
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        plen = int(prompt_lens[i % len(prompt_lens)])
        ids = rng.integers(0, vocab, size=plen).astype(np.int32)
        out.append(Request(
            prompt_ids=ids, max_new=int(max_new[i % len(max_new)]),
            eos_id=eos_id,
            sampling=Sampling(temperature=temperature, seed=seed + i)))
    return out


def shared_prefix_requests(n: int, *, vocab: int, n_prefixes: int,
                           prefix_len: int, suffix_lens: Sequence[int],
                           max_new: Sequence[int], seed: int = 0,
                           sessions: int = 0,
                           temperature: float = 0.0,
                           eos_id: Optional[int] = None
                           ) -> List[Request]:
    """The prefix-heavy workload every serving PR is judged on: a
    seeded pool of ``n_prefixes`` shared "system prompts" of
    ``prefix_len`` tokens, assigned round-robin (so reuse is
    deterministic, not a sampling accident), each followed by a
    per-request random suffix (``suffix_lens`` cycled).  With
    ``sessions > 0`` requests also carry round-robin session ids —
    the fleet router's session-affinity signal.  Same determinism
    contract as :func:`synthetic_requests`: one rng seeded by ``seed``,
    per-request sampling seeds ``seed + i``, replay-identical."""
    rng = np.random.default_rng(seed)
    prefixes = [rng.integers(0, vocab, size=int(prefix_len))
                .astype(np.int32) for _ in range(max(1, n_prefixes))]
    out = []
    for i in range(n):
        prefix = prefixes[i % len(prefixes)]
        slen = int(suffix_lens[i % len(suffix_lens)])
        suffix = rng.integers(0, vocab, size=slen).astype(np.int32)
        out.append(Request(
            prompt_ids=np.concatenate([prefix, suffix]),
            max_new=int(max_new[i % len(max_new)]), eos_id=eos_id,
            session_id=(f"session-{i % sessions}" if sessions else None),
            sampling=Sampling(temperature=temperature, seed=seed + i)))
    return out


def open_loop(requests: Sequence[Request], *, rate: float = 0.0,
              stagger_steps: int = 2, seed: int = 0
              ) -> "OpenLoopTraffic":
    """THE open-loop schedule selector, shared by the serve frontend's
    ``--synthetic`` mode, the bench serve legs and the fleet workload
    replayer (one copy of the rate>0 → Poisson, else step-staggered
    choice): ``rate > 0`` drives wall-clock Poisson arrivals at that
    rate; ``rate == 0`` pins arrivals to engine ticks every
    ``stagger_steps`` steps (fully deterministic)."""
    if rate > 0:
        return OpenLoopTraffic(
            requests, poisson_arrivals(len(requests), rate, seed=seed))
    return OpenLoopTraffic(
        requests,
        staggered_arrivals(len(requests), every_steps=stagger_steps),
        by_step=True)


class OpenLoopTraffic:
    """Feeds requests into an engine on an open-loop schedule.

    ``arrivals`` are either seconds-from-start floats (wall-clock mode)
    or engine-TICK ints (``by_step=True``, deterministic mode — ticks
    are the engine's loop-iteration clock, which advances even while
    the slot array is idle, so a sparse schedule can never stall
    waiting for a decode step that will never happen).  The engine
    calls :meth:`pump` at every loop iteration; due requests are
    submitted with their SCHEDULED arrival time so queueing delay
    counts into TTFT (wall-clock mode) even when the engine was busy."""

    def __init__(self, requests: Sequence[Request],
                 arrivals: Sequence[float], *, by_step: bool = False):
        if len(requests) != len(arrivals):
            raise ValueError("one arrival per request")
        order = np.argsort(np.asarray(arrivals, float), kind="stable")
        self._pending = [(float(arrivals[i]), requests[i]) for i in order]
        self.by_step = by_step
        self._start: Optional[float] = None
        self.submitted = 0

    @property
    def exhausted(self) -> bool:
        return not self._pending

    def drain(self) -> List[Request]:
        """Hand back every not-yet-submitted request (preemption: the
        engine snapshots them next to the drained queue so a resubmit
        covers the WHOLE planned workload)."""
        out = [r for _, r in self._pending]
        self._pending.clear()
        return out

    def pump(self, engine) -> int:
        """Submit every request whose arrival is due; returns how many."""
        if self._start is None:
            self._start = time.perf_counter()
        now_clock = time.perf_counter()
        clock = float(engine.ticks) if self.by_step \
            else now_clock - self._start
        n = 0
        while self._pending and self._pending[0][0] <= clock:
            at, req = self._pending.pop(0)
            engine.submit(req, arrival_s=(
                None if self.by_step else self._start + at))
            self.submitted += 1
            n += 1
        return n

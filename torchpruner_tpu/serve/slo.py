"""Live SLO monitoring for the serving engine.

The obs histograms answer "what were the latency percentiles of this
run" — after the run.  A serving endpoint needs the live version:
"is the p99 over threshold *right now*".  :class:`SLOMonitor` keeps
bounded rolling windows of the engine's TTFT and per-token latency
observations, re-computes the rolling p99s every ``check_every_steps``
step boundaries, and on a threshold crossing:

- bumps ``serve_slo_breach_total`` (plus the per-metric
  ``serve_slo_breach_<metric>_total``) — the Prometheus counter an
  alert fires on;
- ledgers a ``serve``/``slo_breach`` record (threshold, observed value,
  window size, engine step) so the breach is provenance, joined to the
  checkpoint digests serving at the time;
- keeps ``serve_ttft_p99_rolling_s`` / ``serve_token_p99_rolling_s``
  gauges current either way, so ``GET /metrics`` always shows the live
  tail.

Breaches count *episodes*, not checks: a sustained breach increments
once on entry and re-arms only after the metric recovers below
threshold — a 10-minute incident is one breach, not 600.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional

import numpy as np

from torchpruner_tpu import obs


class SLOMonitor:
    """See module docstring.  Thresholds are seconds; ``None`` disables
    that metric's gate (the rolling gauges still export)."""

    def __init__(self, ttft_p99_s: Optional[float] = None,
                 token_p99_s: Optional[float] = None,
                 window: int = 256, check_every_steps: int = 8,
                 min_samples: int = 8):
        self.thresholds: Dict[str, Optional[float]] = {
            "ttft": ttft_p99_s, "token": token_p99_s}
        self.window = int(window)
        self.check_every_steps = max(1, int(check_every_steps))
        self.min_samples = max(1, int(min_samples))
        self._obs: Dict[str, deque] = {
            "ttft": deque(maxlen=self.window),
            "token": deque(maxlen=self.window)}
        self._in_breach: Dict[str, bool] = {"ttft": False, "token": False}
        self._last_check_step = -1
        #: check() runs on the engine thread (maybe_check) AND on
        #: /metrics scrape threads, while on_ttft/on_token append from
        #: the engine thread — the lock covers BOTH the episode
        #: accounting (an incident double-counted, a recovery consumed)
        #: and the deque iteration (append mid-iteration raises)
        self._lock = threading.Lock()
        self.breaches_total = 0
        self.rolling: Dict[str, Optional[float]] = {"ttft": None,
                                                    "token": None}

    # -- engine hooks -------------------------------------------------------

    def on_ttft(self, seconds: float) -> None:
        with self._lock:
            self._obs["ttft"].append(float(seconds))

    def on_token(self, seconds: float) -> None:
        with self._lock:
            self._obs["token"].append(float(seconds))

    def maybe_check(self, step: int) -> None:
        """Called at engine step boundaries; cheap no-op between check
        intervals."""
        if step - self._last_check_step < self.check_every_steps:
            return
        self._last_check_step = step
        self.check(step)

    # -- the check ----------------------------------------------------------

    def check(self, step: int = 0) -> Dict[str, Optional[float]]:
        """Recompute rolling p99s, export gauges, count breach episodes
        (thread-safe).  Returns the rolling values."""
        with self._lock:
            return self._check_locked(step)

    def _check_locked(self, step: int) -> Dict[str, Optional[float]]:
        for metric, samples in self._obs.items():
            if not samples:
                continue
            p99 = float(np.percentile(np.asarray(samples), 99))
            self.rolling[metric] = p99
            obs.gauge_set(
                f"serve_{metric}_p99_rolling_s", p99,
                help=f"rolling p99 of serve {metric} latency over the "
                     f"last {self.window} observations")
            limit = self.thresholds.get(metric)
            if limit is None or len(samples) < self.min_samples:
                continue
            if p99 > limit and not self._in_breach[metric]:
                self._in_breach[metric] = True
                self.breaches_total += 1
                obs.inc("serve_slo_breach_total",
                        help="SLO breach episodes (rolling p99 crossed "
                             "its threshold; re-arms on recovery)")
                obs.inc(f"serve_slo_breach_{metric}_total")
                obs.record_serve(
                    kind="slo_breach", metric=metric, p99_s=p99,
                    threshold_s=limit, window=len(samples), step=step)
            elif p99 <= limit:
                self._in_breach[metric] = False
        return dict(self.rolling)

    def in_breach_any(self) -> bool:
        """True while ANY gated metric's rolling p99 sits over its
        threshold — the readiness-degradation signal ``/healthz``
        (and through it the fleet router) keys off."""
        with self._lock:
            return any(self._in_breach.values())

    def snapshot(self) -> Dict[str, object]:
        """The ``/stats`` block: rolling values, thresholds, breach
        count, in-breach flags."""
        return {
            "ttft_p99_rolling_ms": (round(self.rolling["ttft"] * 1e3, 3)
                                    if self.rolling["ttft"] is not None
                                    else None),
            "token_p99_rolling_ms": (round(self.rolling["token"] * 1e3, 3)
                                     if self.rolling["token"] is not None
                                     else None),
            "thresholds_ms": {
                k: (round(v * 1e3, 3) if v is not None else None)
                for k, v in self.thresholds.items()},
            "breaches_total": self.breaches_total,
            "in_breach": dict(self._in_breach),
        }

"""Live SLO monitoring for the serving engine.

The obs histograms answer "what were the latency percentiles of this
run" — after the run.  A serving endpoint needs the live version:
"is the p99 over threshold *right now*".  :class:`SLOMonitor` keeps
bounded rolling windows of the engine's TTFT, per-token latency, and
queue-age observations, re-computes the rolling p99s every
``check_every_steps`` step boundaries, and on a threshold crossing:

- bumps ``serve_slo_breach_total`` (plus the per-metric
  ``serve_slo_breach_<metric>_total``) — the Prometheus counter an
  alert fires on;
- ledgers a ``serve``/``slo_breach`` record (threshold, observed value,
  window size, engine step) so the breach is provenance, joined to the
  checkpoint digests serving at the time;
- keeps ``serve_ttft_p99_rolling_s`` / ``serve_token_p99_rolling_s``
  gauges current either way, so ``GET /metrics`` always shows the live
  tail.

Breaches count *episodes*, not checks: a sustained breach increments
once on entry and re-arms only after the metric recovers below
threshold — a 10-minute incident is one breach, not 600.

**Burn rate** (the SRE multi-window alert, Google SRE workbook ch. 5):
observations carry timestamps, so on each check the monitor also
computes, per gated metric, the fraction of observations over
threshold within a FAST window (default 15 s — catches an incident
quickly) and a SLOW window (default 120 s — rejects blips), each
divided by the error budget (default 1%: an SLO permits 1% of
requests over threshold).  When BOTH burns sit at/over
``burn_threshold`` (default 10× budget) a ``slo_burn`` alert fires —
once per episode, re-arming when the fast burn recovers — bumping
``slo_burn_alerts_total``, exporting ``slo_burn_<metric>_fast`` /
``_slow`` gauges (which ride ``obs diff --gate``), and ledgering a
``serve``/``slo_burn`` record.  The fleet drill harness exits non-zero
on any ledgered burn alert, which is what the CI planted
``slow_replica_ms`` drill asserts.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional

import numpy as np

from torchpruner_tpu import obs

#: burn-rate defaults — windows sized so a CI-scale drill (tens of
#: seconds) spans both; production drivers pass their own
BURN_FAST_WINDOW_S = 15.0
BURN_SLOW_WINDOW_S = 120.0
BURN_BUDGET = 0.01
BURN_THRESHOLD = 10.0


class SLOMonitor:
    """See module docstring.  Thresholds are seconds; ``None`` disables
    that metric's gate (the rolling gauges still export)."""

    METRICS = ("ttft", "token", "queue")

    def __init__(self, ttft_p99_s: Optional[float] = None,
                 token_p99_s: Optional[float] = None,
                 queue_p99_s: Optional[float] = None,
                 window: int = 256, check_every_steps: int = 8,
                 min_samples: int = 8,
                 burn_fast_window_s: float = BURN_FAST_WINDOW_S,
                 burn_slow_window_s: float = BURN_SLOW_WINDOW_S,
                 burn_budget: float = BURN_BUDGET,
                 burn_threshold: float = BURN_THRESHOLD):
        self.thresholds: Dict[str, Optional[float]] = {
            "ttft": ttft_p99_s, "token": token_p99_s,
            "queue": queue_p99_s}
        self.window = int(window)
        self.check_every_steps = max(1, int(check_every_steps))
        self.min_samples = max(1, int(min_samples))
        self.burn_fast_window_s = float(burn_fast_window_s)
        self.burn_slow_window_s = float(burn_slow_window_s)
        self.burn_budget = max(1e-6, float(burn_budget))
        self.burn_threshold = float(burn_threshold)
        #: observations are (wall-clock ts, seconds) pairs — the burn
        #: windows are TIME windows, not count windows, so the math
        #: stays true when traffic is bursty
        self._obs: Dict[str, deque] = {
            m: deque(maxlen=self.window) for m in self.METRICS}
        self._in_breach: Dict[str, bool] = {
            m: False for m in self.METRICS}
        self._in_burn: Dict[str, bool] = {
            m: False for m in self.METRICS}
        #: wall-clock start of the current burn episode per metric —
        #: observed into ``slo_burn_episode_seconds`` at re-arm
        self._burn_started: Dict[str, Optional[float]] = {
            m: None for m in self.METRICS}
        self._last_check_step = -1
        #: check() runs on the engine thread (maybe_check) AND on
        #: /metrics scrape threads, while on_ttft/on_token append from
        #: the engine thread — the lock covers BOTH the episode
        #: accounting (an incident double-counted, a recovery consumed)
        #: and the deque iteration (append mid-iteration raises)
        self._lock = threading.Lock()
        self.breaches_total = 0
        self.burn_alerts_total = 0
        self.rolling: Dict[str, Optional[float]] = {
            m: None for m in self.METRICS}

    # -- engine hooks -------------------------------------------------------

    def on_ttft(self, seconds: float, ts: Optional[float] = None) -> None:
        self._observe("ttft", seconds, ts)

    def on_token(self, seconds: float, ts: Optional[float] = None) -> None:
        self._observe("token", seconds, ts)

    def on_queue(self, seconds: float, ts: Optional[float] = None) -> None:
        """Queue age at admission (scheduler hook)."""
        self._observe("queue", seconds, ts)

    def _observe(self, metric: str, seconds: float,
                 ts: Optional[float]) -> None:
        t = time.time() if ts is None else float(ts)
        with self._lock:
            self._obs[metric].append((t, float(seconds)))

    def maybe_check(self, step: int) -> None:
        """Called at engine step boundaries; cheap no-op between check
        intervals."""
        if step - self._last_check_step < self.check_every_steps:
            return
        self._last_check_step = step
        self.check(step)

    # -- the check ----------------------------------------------------------

    def check(self, step: int = 0, now: Optional[float] = None
              ) -> Dict[str, Optional[float]]:
        """Recompute rolling p99s, export gauges, count breach episodes
        (thread-safe).  Returns the rolling values.  ``now`` anchors
        the burn windows (defaults to wall clock; tests pass it with
        synthetic observation timestamps)."""
        with self._lock:
            return self._check_locked(step, now)

    def _check_locked(self, step: int, now: Optional[float] = None
                      ) -> Dict[str, Optional[float]]:
        if now is None:
            now = time.time()
        for metric, samples in self._obs.items():
            if not samples:
                continue
            values = np.asarray([v for _, v in samples])
            p99 = float(np.percentile(values, 99))
            self.rolling[metric] = p99
            obs.gauge_set(
                f"serve_{metric}_p99_rolling_s", p99,
                help=f"rolling p99 of serve {metric} latency over the "
                     f"last {self.window} observations")
            limit = self.thresholds.get(metric)
            if limit is None or len(samples) < self.min_samples:
                continue
            if p99 > limit and not self._in_breach[metric]:
                self._in_breach[metric] = True
                self.breaches_total += 1
                obs.inc("serve_slo_breach_total",
                        help="SLO breach episodes (rolling p99 crossed "
                             "its threshold; re-arms on recovery)")
                obs.inc(f"serve_slo_breach_{metric}_total")
                obs.record_serve(
                    kind="slo_breach", metric=metric, p99_s=p99,
                    threshold_s=limit, window=len(samples), step=step)
            elif p99 <= limit:
                self._in_breach[metric] = False
            self._burn_locked(metric, samples, limit, now, step)
        obs.gauge_set(
            "slo_burn_active", float(sum(self._in_burn.values())),
            help="gated metrics currently inside a burn episode "
                 "(0 = healthy; rides obs diff and the watch board)")
        return dict(self.rolling)

    def _burn_locked(self, metric: str, samples, limit: float,
                     now: float, step: int) -> None:
        """Multi-window burn-rate evaluation for one gated metric —
        caller holds the lock and has already verified a threshold."""
        burns: Dict[str, Optional[float]] = {}
        counts: Dict[str, int] = {}
        for which, win_s in (("fast", self.burn_fast_window_s),
                             ("slow", self.burn_slow_window_s)):
            sub = [v for ts, v in samples if ts >= now - win_s]
            counts[which] = len(sub)
            if not sub:
                burns[which] = 0.0
                continue
            bad = sum(1 for v in sub if v > limit)
            burns[which] = (bad / len(sub)) / self.burn_budget
            obs.gauge_set(
                f"slo_burn_{metric}_{which}", burns[which],
                help=f"{metric} error-budget burn rate over the "
                     f"{which} window ({win_s:.0f}s; alert at "
                     f"{self.burn_threshold:g}×)")
        firing = (counts["fast"] >= self.min_samples
                  and burns["fast"] >= self.burn_threshold
                  and burns["slow"] >= self.burn_threshold)
        if firing and not self._in_burn[metric]:
            self._in_burn[metric] = True
            self._burn_started[metric] = now
            self.burn_alerts_total += 1
            obs.inc("slo_burn_alerts_total",
                    help="multi-window burn-rate alert episodes (fast "
                         "AND slow burn over threshold; re-arms when "
                         "the fast burn recovers)")
            obs.record_serve(
                kind="slo_burn", metric=metric,
                burn_fast=round(burns["fast"], 3),
                burn_slow=round(burns["slow"], 3),
                budget=self.burn_budget,
                burn_threshold=self.burn_threshold,
                fast_window_s=self.burn_fast_window_s,
                slow_window_s=self.burn_slow_window_s,
                threshold_s=limit, step=step,
                # the trigger instant, carried verbatim when the fleet
                # epilogue re-records this alert — the incident
                # correlator anchors its lookback here, not at the
                # re-record time (obs.incident)
                burn_ts=round(now, 6))
        elif (burns["fast"] or 0.0) < self.burn_threshold:
            if self._in_burn[metric]:
                started = self._burn_started.get(metric)
                if started is not None:
                    obs.observe(
                        "slo_burn_episode_seconds",
                        max(0.0, now - started),
                        help="burn-episode duration: alert fire → fast-"
                             "window recovery (observed at re-arm)")
                self._burn_started[metric] = None
            self._in_burn[metric] = False

    def in_breach_any(self) -> bool:
        """True while ANY gated metric's rolling p99 sits over its
        threshold — the readiness-degradation signal ``/healthz``
        (and through it the fleet router) keys off."""
        with self._lock:
            return any(self._in_breach.values())

    def snapshot(self) -> Dict[str, object]:
        """The ``/stats`` block: rolling values, thresholds, breach
        count, in-breach flags (shape kept stable for clients; the
        burn fields are additive)."""
        return {
            "ttft_p99_rolling_ms": (round(self.rolling["ttft"] * 1e3, 3)
                                    if self.rolling["ttft"] is not None
                                    else None),
            "token_p99_rolling_ms": (round(self.rolling["token"] * 1e3, 3)
                                     if self.rolling["token"] is not None
                                     else None),
            "thresholds_ms": {
                k: (round(v * 1e3, 3) if v is not None else None)
                for k, v in self.thresholds.items()},
            "breaches_total": self.breaches_total,
            "in_breach": dict(self._in_breach),
            "burn_alerts_total": self.burn_alerts_total,
            "in_burn": dict(self._in_burn),
        }

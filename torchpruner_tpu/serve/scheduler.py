"""Continuous-batching request scheduler.

Open-loop admission control over the fixed slot array: requests queue
FIFO; at every DECODE-STEP BOUNDARY the engine asks the scheduler to
(1) admit queued requests into free slots (prefill hand-off) and
(2) evict finished ones (slot + page recycling).  Mid-sequence the
compiled step is never perturbed — admission changes only the host-side
slot tables (positions, current tokens, sampling vectors) that are
passed into the SAME compiled program each step, which is what makes
the batching "continuous": one XLA executable serves a ragged,
ever-changing mix of requests.

Thread-safety: ``submit`` may be called from frontend threads (HTTP
handlers) while the engine loop runs; the queue is guarded by a lock.
Everything else is engine-loop-only.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional

from torchpruner_tpu import obs
from torchpruner_tpu.obs import reqtrace
from torchpruner_tpu.serve.allocator import KVCacheAllocator
from torchpruner_tpu.serve.request import (
    ACTIVE,
    DONE,
    DRAINED,
    QUEUED,
    SHED,
    Request,
)

_REJECTED_HELP = ("submissions rejected (per-reason twins: "
                  "serve_rejected_<reason>_total)")


class Scheduler:
    """FIFO queue + slot-table bookkeeping (see module docstring).

    ``queue_bound > 0`` bounds the waiting queue: a submission landing
    on a full queue is SHED immediately (state ``shed``, event set)
    instead of queueing unboundedly — the HTTP front end turns that
    into 503 + Retry-After, and the fleet router reuses the same bound
    as its per-replica backpressure signal."""

    def __init__(self, allocator: KVCacheAllocator,
                 queue_bound: int = 0, prefill_token_cap: int = 0):
        self.allocator = allocator
        self.queue_bound = int(queue_bound)
        #: per-engine-step prefill-token budget (chunked prefill): a
        #: long prompt spends at most this much prefill work per step,
        #: so decode cadence for resident requests is bounded below.
        #: 0 = uncapped.
        self.prefill_token_cap = int(prefill_token_cap)
        self._queue: Deque[Request] = deque()
        self._lock = threading.Lock()
        #: recent queue-age-at-admission samples (seconds) — the LIVE
        #: p50/p99 the /stats endpoint serves; the full distribution
        #: rides the serve_queue_wait_seconds histogram
        self._queue_waits: Deque[float] = deque(maxlen=512)
        #: optional per-admission queue-age callback — the engine wires
        #: this to ``SLOMonitor.on_queue`` so queue age joins the
        #: burn-rate evaluation (serve/slo.py)
        self.on_queue_wait = None
        #: slot -> active request
        self.running: Dict[int, Request] = {}
        self.admitted_total = 0
        self.completed_total = 0
        self.shed_total = 0
        #: set when a drain begins: later submissions are REJECTED
        #: (marked drained, event set) instead of queueing forever —
        #: an HTTP client racing a SIGTERM gets an immediate "resubmit
        #: elsewhere" answer, and the drain loop can still terminate
        self.closed = False

    # -- frontend side ------------------------------------------------------

    def submit(self, request: Request,
               arrival_s: Optional[float] = None) -> Request:
        """Enqueue a request (thread-safe).  ``arrival_s`` lets an
        open-loop traffic generator backdate the arrival to its
        SCHEDULED time, so queueing delay counts into TTFT the way it
        would for a real caller."""
        request.arrival_s = (time.perf_counter() if arrival_s is None
                             else arrival_s)
        with self._lock:
            # the closed check shares the queue lock with drain_queue:
            # checked outside it, a submission racing the drain could
            # append AFTER the drain swept the queue — a permanently
            # QUEUED request that keeps has_work() true and spins the
            # SIGTERM'd loop forever
            if self.closed:
                request.state = DRAINED
                request._event.set()
                obs.inc("serve_rejected_total", help=_REJECTED_HELP)
                obs.inc("serve_rejected_drain_total",
                        help="submissions rejected after a drain began")
                return request
            if self.queue_bound and len(self._queue) >= self.queue_bound:
                request.state = SHED
                request._event.set()
                self.shed_total += 1
                obs.inc("serve_rejected_total", help=_REJECTED_HELP)
                obs.inc("serve_rejected_backpressure_total",
                        help="submissions shed by the queue bound "
                             "(503 + Retry-After backpressure)")
                return request
            request.state = QUEUED
            self._queue.append(request)
        obs.inc("serve_requests_total", help="requests submitted")
        return request

    def close(self) -> None:
        """Begin a drain: flip ``closed`` under the queue lock.  Set
        bare (``scheduler.closed = True``) a submission racing the
        drain could observe ``closed == False``, pass the gate, and
        append after the drain swept the queue — the same permanently
        QUEUED hang the ``submit`` gate exists to prevent."""
        with self._lock:
            self.closed = True

    # -- engine side (step boundaries only) ---------------------------------

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def has_work(self) -> bool:
        return bool(self.running) or self.queue_depth > 0

    def admit(self) -> List[Request]:
        """Pop queued requests while a slot (and KV pages) are free;
        returns the newly-admitted batch for the engine to prefill.
        FIFO head-of-line: a too-long request at the head blocks the
        queue rather than being overtaken (no starvation)."""
        out: List[Request] = []
        while True:
            with self._lock:
                if not self._queue:
                    break
                head = self._queue[0]
                lease = self.allocator.allocate(head.id, head.total_len)
                if lease is None:
                    break
                self._queue.popleft()
            head.slot = lease.slot
            head.state = ACTIVE
            # queue age is recorded AT ADMISSION, not at completion —
            # the wait is visible in /stats and the reqtrace budget
            # while the request is still decoding
            head.admitted_s = time.perf_counter()
            if head.arrival_s is not None:
                wait = max(0.0, head.admitted_s - head.arrival_s)
                with self._lock:
                    # /stats handler threads sort this deque live — an
                    # unlocked append could fault their iteration
                    self._queue_waits.append(wait)
                obs.observe("serve_queue_wait_seconds", wait,
                            help="request submit -> slot admission "
                                 "(queue age at admit time)")
                if self.on_queue_wait is not None:
                    self.on_queue_wait(wait)
                reqtrace.stage(head.trace_id, "replica_queue",
                               dur_s=wait, request=head.id)
            self.running[lease.slot] = head
            self.admitted_total += 1
            out.append(head)
        if out:
            obs.inc("serve_admits_total", n=len(out),
                    help="requests admitted into a decode slot")
        self._gauges()
        return out

    def evict(self, request: Request, state: str = DONE) -> None:
        """Release a finished request's slot + pages (step boundary)."""
        slot = request.slot
        request.state = state
        request.done_s = time.perf_counter()
        if slot is not None and self.running.get(slot) is request:
            del self.running[slot]
            self.allocator.release(slot)
        request.slot = None
        self.completed_total += 1
        obs.inc("serve_evictions_total",
                help="slot evictions (request completion or early stop)")
        if state == DONE:
            obs.inc("serve_completed_total", help="requests completed")
        request._event.set()
        self._gauges()

    def prefill_budget(self, chunk: int) -> int:
        """The step's prefill-token budget, floored at one chunk —
        a cap below the chunk width would deadlock the prefill, so the
        floor IS the enforced cap (engine.max_prefill_tokens_step is
        gated against this value, not the raw knob)."""
        if self.prefill_token_cap <= 0:
            return 1 << 30
        return max(self.prefill_token_cap, int(chunk))

    def queue_wait_ms(self) -> Dict[str, float]:
        """Live queue-age percentiles over the recent-admissions window
        (ms) — empty dict before the first admission.  Thread-safe
        (called from /stats handler threads while the engine admits)."""
        with self._lock:
            xs = sorted(self._queue_waits)
        if not xs:
            return {}

        def pct(q: float) -> float:
            i = min(len(xs) - 1, max(0, round(q * (len(xs) - 1))))
            return round(1e3 * xs[int(i)], 3)

        return {"p50": pct(0.50), "p99": pct(0.99)}

    def drain_queue(self) -> List[Request]:
        """Remove and return every not-yet-started request — the
        preemption path: in-flight requests finish, queued ones are
        snapshotted for resubmission."""
        with self._lock:
            out = list(self._queue)
            self._queue.clear()
        self._gauges()
        return out

    def _gauges(self) -> None:
        alloc = self.allocator
        obs.gauge_set("serve_queue_depth", self.queue_depth,
                      help="requests waiting for a slot")
        obs.gauge_set("serve_active_slots", alloc.active_slots,
                      help="slots currently decoding")
        obs.gauge_set("serve_kv_pages_in_use", alloc.pages_in_use,
                      help="KV-cache pages leased to active requests")
        obs.gauge_set("serve_kv_page_occupancy",
                      alloc.pages_in_use / max(1, alloc.page_budget),
                      help="leased KV pages / page budget (0..1)")
        obs.gauge_set("serve_slot_utilization",
                      alloc.active_slots / max(1, alloc.n_slots),
                      help="active decode slots / slot-array width "
                           "(0..1)")
        if alloc.prefix_enabled:
            # emitted ONLY with sharing on, so sharing-off runs (and
            # their committed goldens) carry no serve_prefix_*/shared
            # scalars at all
            obs.gauge_set("serve_kv_pages_shared", alloc.shared_pages,
                          help="prefix-pool pages pinned by at least "
                               "one resident request")
            obs.gauge_set("serve_prefix_pool_used",
                          alloc.prefix_pool_used,
                          help="prefix-pool pages holding published "
                               "K/V (out of prefix_pages)")

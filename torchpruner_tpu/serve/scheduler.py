"""Continuous-batching request scheduler.

Open-loop admission control over the fixed slot array: requests queue
FIFO; at every DECODE-STEP BOUNDARY the engine asks the scheduler to
(1) admit queued requests into free slots (prefill hand-off) and
(2) evict finished ones (slot + page recycling).  Mid-sequence the
compiled step is never perturbed — admission changes only the host-side
slot tables (positions, current tokens, sampling vectors) that are
passed into the SAME compiled program each step, which is what makes
the batching "continuous": one XLA executable serves a ragged,
ever-changing mix of requests.

Thread-safety: ``submit`` may be called from frontend threads (HTTP
handlers) while the engine loop runs; the queue is guarded by a lock.
Everything else is engine-loop-only.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional

from torchpruner_tpu import obs
from torchpruner_tpu.obs import reqtrace
from torchpruner_tpu.serve.allocator import KVCacheAllocator
from torchpruner_tpu.serve.qos import QoS
from torchpruner_tpu.serve.request import (
    ACTIVE,
    DONE,
    DRAINED,
    QUEUED,
    SHED,
    Request,
)

_REJECTED_HELP = ("submissions rejected (per-reason twins: "
                  "serve_rejected_<reason>_total)")


class Scheduler:
    """FIFO queue + slot-table bookkeeping (see module docstring).

    ``queue_bound > 0`` bounds the waiting queue: a submission landing
    on a full queue is SHED immediately (state ``shed``, event set)
    instead of queueing unboundedly — the HTTP front end turns that
    into 503 + Retry-After, and the fleet router reuses the same bound
    as its per-replica backpressure signal."""

    def __init__(self, allocator: KVCacheAllocator,
                 queue_bound: int = 0, prefill_token_cap: int = 0,
                 qos: Optional[QoS] = None):
        self.allocator = allocator
        self.queue_bound = int(queue_bound)
        #: per-engine-step prefill-token budget (chunked prefill): a
        #: long prompt spends at most this much prefill work per step,
        #: so decode cadence for resident requests is bounded below.
        #: 0 = uncapped.
        self.prefill_token_cap = int(prefill_token_cap)
        #: multi-tenant QoS table (serve.qos) — an empty table makes
        #: every path below behave exactly like the pre-QoS FIFO
        self.qos = qos if qos is not None else QoS()
        #: priority class -> FIFO of waiting requests; admission serves
        #: ascending class numbers, FIFO (head-of-line) within a class
        self._queues: Dict[int, Deque[Request]] = {}
        self._lock = threading.Lock()
        #: recent queue-age-at-admission samples (seconds) — the LIVE
        #: p50/p99 the /stats endpoint serves; the full distribution
        #: rides the serve_queue_wait_seconds histogram
        self._queue_waits: Deque[float] = deque(maxlen=512)
        #: optional per-admission queue-age callback — the engine wires
        #: this to ``SLOMonitor.on_queue`` so queue age joins the
        #: burn-rate evaluation (serve/slo.py)
        self.on_queue_wait = None
        #: slot -> active request
        self.running: Dict[int, Request] = {}
        self.admitted_total = 0
        self.completed_total = 0
        self.shed_total = 0
        #: requests preempted back to the queue by a higher-priority
        #: admission (progress restarts on re-admit)
        self.preempted_total = 0
        #: engine-installed guard: ``guard(slot) -> bool`` answers
        #: whether that slot may be preempted RIGHT NOW (the engine
        #: refuses slots mid-chunked-prefill); None = any active slot
        self.preempt_guard = None
        #: set when a drain begins: later submissions are REJECTED
        #: (marked drained, event set) instead of queueing forever —
        #: an HTTP client racing a SIGTERM gets an immediate "resubmit
        #: elsewhere" answer, and the drain loop can still terminate
        self.closed = False

    # -- frontend side ------------------------------------------------------

    def submit(self, request: Request,
               arrival_s: Optional[float] = None) -> Request:
        """Enqueue a request (thread-safe).  ``arrival_s`` lets an
        open-loop traffic generator backdate the arrival to its
        SCHEDULED time, so queueing delay counts into TTFT the way it
        would for a real caller."""
        request.arrival_s = (time.perf_counter() if arrival_s is None
                             else arrival_s)
        pol = self.qos.policy(request.tenant)
        with self._lock:
            # the closed check shares the queue lock with drain_queue:
            # checked outside it, a submission racing the drain could
            # append AFTER the drain swept the queue — a permanently
            # QUEUED request that keeps has_work() true and spins the
            # SIGTERM'd loop forever
            if self.closed:
                request.state = DRAINED
                request._event.set()
                obs.inc("serve_rejected_total", help=_REJECTED_HELP)
                obs.inc("serve_rejected_drain_total",
                        help="submissions rejected after a drain began")
                return request
            if not self.qos.admit_now(request.tenant):
                request.state = SHED
                request._event.set()
                self.shed_total += 1
                obs.inc("serve_rejected_total", help=_REJECTED_HELP)
                obs.inc("serve_rejected_throttle_total",
                        help="submissions shed by a tenant's token "
                             "bucket (rate throttling)")
                self._tenant_shed(request.tenant, "throttle")
                return request
            if self.queue_bound and self._depth_locked() >= self.queue_bound:
                request.state = SHED
                request._event.set()
                self.shed_total += 1
                obs.inc("serve_rejected_total", help=_REJECTED_HELP)
                obs.inc("serve_rejected_backpressure_total",
                        help="submissions shed by the queue bound "
                             "(503 + Retry-After backpressure)")
                self._tenant_shed(request.tenant, "backpressure")
                return request
            request.state = QUEUED
            self._queues.setdefault(pol.priority, deque()).append(request)
        obs.inc("serve_requests_total", help="requests submitted")
        return request

    def _tenant_shed(self, tenant: Optional[str], reason: str) -> None:
        """Per-tenant shed twins of the serve_rejected_* counters."""
        if not tenant:
            return
        obs.inc(f"tenant_{tenant}_shed_total",
                help="this tenant's shed submissions (all reasons)")
        obs.inc(f"tenant_{tenant}_shed_{reason}_total",
                help=f"this tenant's submissions shed by {reason}")

    def close(self) -> None:
        """Begin a drain: flip ``closed`` under the queue lock.  Set
        bare (``scheduler.closed = True``) a submission racing the
        drain could observe ``closed == False``, pass the gate, and
        append after the drain swept the queue — the same permanently
        QUEUED hang the ``submit`` gate exists to prevent."""
        with self._lock:
            self.closed = True

    # -- engine side (step boundaries only) ---------------------------------

    def _depth_locked(self) -> int:
        return sum(len(q) for q in self._queues.values())

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return self._depth_locked()

    def has_work(self) -> bool:
        return bool(self.running) or self.queue_depth > 0

    def _head_locked(self):
        """Highest-priority non-empty queue and its head request."""
        for prio in sorted(self._queues):
            q = self._queues[prio]
            if q:
                return q, q[0]
        return None, None

    def _pick_victim_locked(self, priority: int) -> Optional[Request]:
        """The preemption victim for an admission at ``priority``: the
        YOUNGEST active request of a strictly lower (larger-number)
        preemptible class — last in, first preempted, so long-running
        batch work accumulates the least wasted progress.  The engine's
        ``preempt_guard`` vetoes slots mid-chunked-prefill."""
        victim: Optional[Request] = None
        for req in self.running.values():
            pol = self.qos.policy(req.tenant)
            if pol.priority <= priority or not pol.preemptible:
                continue
            if req.state != ACTIVE or req.slot is None:
                continue
            if self.preempt_guard is not None \
                    and not self.preempt_guard(req.slot):
                continue
            if victim is None or (req.admitted_s or 0.0) \
                    > (victim.admitted_s or 0.0):
                victim = req
        return victim

    def _preempt_locked(self, victim: Request) -> None:
        """Evict an ACTIVE request back to the FRONT of its class
        queue, releasing slot + pages and resetting generation progress
        (tokens restart from the prompt on re-admission).  Called only
        from :meth:`admit` — i.e. only at a decode-step boundary, so
        the compiled step never observes a half-evicted slot."""
        slot = victim.slot
        if slot is not None and self.running.get(slot) is victim:
            del self.running[slot]
            self.allocator.release(slot)
        victim.slot = None
        victim.state = QUEUED
        victim.tokens.clear()
        victim.token_gaps_s.clear()
        victim.first_token_s = None
        victim.prefill_s = None
        victim.admitted_s = None
        victim.done_s = None
        victim.prefix_hit_tokens = 0
        victim.prefilled_tokens = 0
        victim.served_by = None
        victim.preemptions += 1
        self.preempted_total += 1
        pol = self.qos.policy(victim.tenant)
        self._queues.setdefault(pol.priority, deque()).appendleft(victim)
        obs.inc("serve_preempted_total",
                help="active requests preempted back to the queue by a "
                     "higher-priority admission (step boundary only)")
        if victim.tenant:
            obs.inc(f"tenant_{victim.tenant}_preempted_total",
                    help="this tenant's requests preempted by a "
                         "higher-priority admission")
        reqtrace.stage(victim.trace_id, "preempted", request=victim.id,
                       preemptions=victim.preemptions)

    def admit(self) -> List[Request]:
        """Pop queued requests while a slot (and KV pages) are free;
        returns the newly-admitted batch for the engine to prefill.
        Admission serves priority classes in ascending order, FIFO
        head-of-line WITHIN a class: a too-long request at the head
        blocks its queue rather than being overtaken (no starvation).
        When the head is blocked on capacity and a strictly lower
        (preemptible) class holds slots, the youngest such active
        request is preempted — here and only here, so preemption is
        step-boundary-exact by construction.  An over-quota head is
        SHED (``serve_rejected_quota_total``) instead of blocking: its
        footprint is the tenant's own doing."""
        out: List[Request] = []
        while True:
            with self._lock:
                q, head = self._head_locked()
                if head is None:
                    break
                pol = self.qos.policy(head.tenant)
                if self.allocator.exceeds_quota(
                        head.tenant, head.total_len, pol.page_quota):
                    q.popleft()
                    head.state = SHED
                    head._event.set()
                    self.shed_total += 1
                    obs.inc("serve_rejected_total", help=_REJECTED_HELP)
                    obs.inc("serve_rejected_quota_total",
                            help="admissions shed because the tenant "
                                 "would exceed its KV-page quota")
                    self._tenant_shed(head.tenant, "quota")
                    continue
                lease = self.allocator.allocate(
                    head.id, head.total_len, tenant=head.tenant)
                if lease is None:
                    victim = self._pick_victim_locked(pol.priority)
                    if victim is None:
                        break
                    self._preempt_locked(victim)
                    continue
                q.popleft()
            head.slot = lease.slot
            head.state = ACTIVE
            # queue age is recorded AT ADMISSION, not at completion —
            # the wait is visible in /stats and the reqtrace budget
            # while the request is still decoding
            head.admitted_s = time.perf_counter()
            if head.arrival_s is not None:
                wait = max(0.0, head.admitted_s - head.arrival_s)
                with self._lock:
                    # /stats handler threads sort this deque live — an
                    # unlocked append could fault their iteration
                    self._queue_waits.append(wait)
                obs.observe("serve_queue_wait_seconds", wait,
                            help="request submit -> slot admission "
                                 "(queue age at admit time)")
                if self.on_queue_wait is not None:
                    self.on_queue_wait(wait)
                reqtrace.stage(head.trace_id, "replica_queue",
                               dur_s=wait, request=head.id)
            with self._lock:
                # /stats and preemption scans read running under the
                # lock — publish the slot assignment the same way
                self.running[lease.slot] = head
            self.admitted_total += 1
            out.append(head)
        if out:
            obs.inc("serve_admits_total", n=len(out),
                    help="requests admitted into a decode slot")
        self._gauges()
        return out

    def evict(self, request: Request, state: str = DONE) -> None:
        """Release a finished request's slot + pages (step boundary)."""
        slot = request.slot
        request.state = state
        request.done_s = time.perf_counter()
        if slot is not None and self.running.get(slot) is request:
            del self.running[slot]
            self.allocator.release(slot)
        request.slot = None
        self.completed_total += 1
        obs.inc("serve_evictions_total",
                help="slot evictions (request completion or early stop)")
        if state == DONE:
            obs.inc("serve_completed_total", help="requests completed")
        request._event.set()
        self._gauges()

    def prefill_budget(self, chunk: int) -> int:
        """The step's prefill-token budget, floored at one chunk —
        a cap below the chunk width would deadlock the prefill, so the
        floor IS the enforced cap (engine.max_prefill_tokens_step is
        gated against this value, not the raw knob)."""
        if self.prefill_token_cap <= 0:
            return 1 << 30
        return max(self.prefill_token_cap, int(chunk))

    def queue_wait_ms(self) -> Dict[str, float]:
        """Live queue-age percentiles over the recent-admissions window
        (ms) — empty dict before the first admission.  Thread-safe
        (called from /stats handler threads while the engine admits)."""
        with self._lock:
            xs = sorted(self._queue_waits)
        if not xs:
            return {}

        def pct(q: float) -> float:
            i = min(len(xs) - 1, max(0, round(q * (len(xs) - 1))))
            return round(1e3 * xs[int(i)], 3)

        return {"p50": pct(0.50), "p99": pct(0.99)}

    def drain_queue(self) -> List[Request]:
        """Remove and return every not-yet-started request — the
        preemption path: in-flight requests finish, queued ones are
        snapshotted for resubmission."""
        with self._lock:
            out = [r for prio in sorted(self._queues)
                   for r in self._queues[prio]]
            for q in self._queues.values():
                q.clear()
        self._gauges()
        return out

    def _gauges(self) -> None:
        alloc = self.allocator
        obs.gauge_set("serve_queue_depth", self.queue_depth,
                      help="requests waiting for a slot")
        obs.gauge_set("serve_active_slots", alloc.active_slots,
                      help="slots currently decoding")
        obs.gauge_set("serve_kv_pages_in_use", alloc.pages_in_use,
                      help="KV-cache pages leased to active requests")
        obs.gauge_set("serve_kv_page_occupancy",
                      alloc.pages_in_use / max(1, alloc.page_budget),
                      help="leased KV pages / page budget (0..1)")
        obs.gauge_set("serve_slot_utilization",
                      alloc.active_slots / max(1, alloc.n_slots),
                      help="active decode slots / slot-array width "
                           "(0..1)")
        if alloc.prefix_enabled:
            # emitted ONLY with sharing on, so sharing-off runs (and
            # their committed goldens) carry no serve_prefix_*/shared
            # scalars at all
            obs.gauge_set("serve_kv_pages_shared", alloc.shared_pages,
                          help="prefix-pool pages pinned by at least "
                               "one resident request")
            obs.gauge_set("serve_prefix_pool_used",
                          alloc.prefix_pool_used,
                          help="prefix-pool pages holding published "
                               "K/V (out of prefix_pages)")

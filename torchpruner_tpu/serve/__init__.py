"""``torchpruner_tpu.serve`` — continuous-batching inference on the
pruned decode path.

The runtime that turns a pruned checkpoint into sustained tokens/s and
tail latency instead of smaller params/FLOPs counters (ROADMAP item 1):

- :class:`~torchpruner_tpu.serve.request.Request` /
  :class:`~torchpruner_tpu.serve.request.Sampling` — one generation job
  with per-request sampling.
- :class:`~torchpruner_tpu.serve.allocator.KVCacheAllocator` —
  lane-aligned bucketed slot/page bookkeeping over the static serving
  cache (recycling without retrace).
- :class:`~torchpruner_tpu.serve.scheduler.Scheduler` — FIFO admission
  / eviction at decode-step boundaries.
- :class:`~torchpruner_tpu.serve.engine.ServeEngine` — the engine:
  bucketed prefill → shared slot-array decode (one compiled step for a
  ragged request mix), checkpoint hot-swap, SIGTERM drain.
- :mod:`~torchpruner_tpu.serve.traffic` — open-loop Poisson /
  step-staggered synthetic workloads (bench ``serve`` leg, CI smoke).
- :class:`~torchpruner_tpu.serve.slo.SLOMonitor` — live rolling-p99
  TTFT / per-token SLO gates (breach episodes counted + ledgered).
- ``python -m torchpruner_tpu serve <preset>`` — the endpoint
  (:mod:`~torchpruner_tpu.serve.frontend`): HTTP, stdin, or synthetic
  traffic modes, obs-instrumented end to end.
"""

from torchpruner_tpu.serve.allocator import (
    KVCacheAllocator,
    PrefixTrie,
    aligned_len,
    bucket_for,
    prefill_buckets,
)
from torchpruner_tpu.serve.engine import (
    ServeEngine,
    sample_tokens,
    vocab_of,
)
from torchpruner_tpu.serve.qos import (
    QoS,
    TenantPolicy,
    TokenBucket,
)
from torchpruner_tpu.serve.request import (
    Request,
    Sampling,
    request_from_dict,
)
from torchpruner_tpu.serve.scheduler import Scheduler
from torchpruner_tpu.serve.slo import SLOMonitor
from torchpruner_tpu.serve.traffic import (
    OpenLoopTraffic,
    open_loop,
    poisson_arrivals,
    shared_prefix_requests,
    staggered_arrivals,
    synthetic_requests,
)

__all__ = [
    "Request", "Sampling", "KVCacheAllocator", "PrefixTrie", "Scheduler",
    "ServeEngine", "OpenLoopTraffic", "open_loop", "poisson_arrivals",
    "staggered_arrivals", "synthetic_requests", "shared_prefix_requests",
    "aligned_len", "bucket_for", "prefill_buckets", "sample_tokens",
    "vocab_of", "SLOMonitor", "request_from_dict",
    "QoS", "TenantPolicy", "TokenBucket",
]

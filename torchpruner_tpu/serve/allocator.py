"""Bucketed KV-cache allocation for the serving slot array.

The physical cache is one static ``(n_slots, max_len, H, Dh)`` buffer
per attention layer (generate.init_cache) — static shapes are the TPU
contract, so admission control happens in HOST bookkeeping, not device
reallocation.  This module owns that bookkeeping:

- **Lane-aligned buckets** — page and prefill-bucket sizes come from
  the same 8-sublane / 128-lane alignment ladder ``prune_by_scores``
  rounds kept widths to (core.pruner.bucket_drop, SURVEY.md §7): a
  bounded, hardware-shaped set of compiled prefill lengths means a
  bounded total compile bill, exactly the recompilation-economics
  argument made for prune schedules.
- **Pages** — each slot's ``max_len`` positions are divided into pages
  of ``page_len`` tokens.  A request is admitted only when a free slot
  has enough pages for ``prompt + max_new``; the engine draws down a
  shared page budget so obs can report KV residency
  (``serve_kv_pages_in_use``) and an operator can cap it below
  ``n_slots * pages_per_slot`` (over-subscription guard for mixed
  long/short traffic).
- **Recycling without retrace** — freeing a slot is a host-side list
  append; the device buffer is NOT zeroed.  Stale K/V from the previous
  occupant is harmless by construction: a position ``t`` of a slot's
  cache only becomes attendable once that slot's decode position
  reaches ``t``, and the decode step writes position ``t`` before
  reading it (generate._decode_attention masks ``t > pos``).  The
  ragged-parity tests pin this by poisoning the cache and checking
  bit-identical logits.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

#: the TPU tiling ladder shared with core.pruner.bucket_drop: vector
#: lanes are 128 wide, sublanes 8 deep — multiples tile the MXU/VPU
#: cleanly and bound the distinct-shape set
SUBLANE = 8
LANE = 128


def aligned_len(n: int) -> int:
    """Round ``n`` up the lane-alignment ladder: to a multiple of 8
    below 128, to a multiple of 128 above — the same rounding direction
    (up = conservative) as ``bucket_drop``'s kept-width rule."""
    if n <= 0:
        return SUBLANE
    if n <= LANE:
        return -(-n // SUBLANE) * SUBLANE
    return -(-n // LANE) * LANE


def prefill_buckets(max_prompt: int) -> List[int]:
    """The bucketed prefill-length ladder up to ``max_prompt``: every
    aligned length {8, 16, .., 128, 256, ..} — one compiled prefill
    program per bucket actually used, never one per prompt length.
    The LAST bucket is ``max_prompt`` itself (possibly unaligned):
    prefill caches insert into the serving cache's ``max_len`` rows, so
    a bucket may never exceed the physical slot length."""
    out, n = [], SUBLANE
    while n < max_prompt:
        out.append(n)
        n = aligned_len(n + 1)
    out.append(max_prompt)
    return out


def bucket_for(n: int, buckets: List[int]) -> int:
    """Smallest bucket holding ``n`` tokens."""
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"prompt length {n} exceeds the largest prefill "
                     f"bucket {buckets[-1]}")


@dataclass
class SlotLease:
    """One admitted request's residency: which slot, how many pages."""

    slot: int
    pages: int
    request_id: int


@dataclass
class KVCacheAllocator:
    """Slot + page bookkeeping over the static serving cache (see
    module docstring).  Pure host state — O(n_slots) lists, no device
    handles — so the scheduler can consult it at every step boundary
    for free."""

    n_slots: int
    max_len: int
    page_len: int = 0
    #: optional global page budget (< n_slots * pages_per_slot caps
    #: total KV residency below the physical buffer)
    page_budget: int = 0
    _free_slots: List[int] = field(default_factory=list)
    _leases: Dict[int, SlotLease] = field(default_factory=dict)
    pages_in_use: int = 0
    total_evictions: int = 0

    def __post_init__(self):
        if self.page_len <= 0:
            # default page: one lane-aligned chunk, capped at the slot
            self.page_len = min(aligned_len(min(self.max_len, LANE)),
                                self.max_len)
        self.page_len = min(self.page_len, self.max_len)
        self._free_slots = list(range(self.n_slots))[::-1]  # pop() -> 0 first
        if self.page_budget <= 0:
            self.page_budget = self.n_slots * self.pages_per_slot

    @property
    def pages_per_slot(self) -> int:
        return -(-self.max_len // self.page_len)

    def pages_needed(self, total_len: int) -> int:
        return -(-total_len // self.page_len)

    def can_admit(self, total_len: int) -> bool:
        if total_len > self.max_len:
            return False
        need = self.pages_needed(total_len)
        return bool(self._free_slots) and \
            self.pages_in_use + need <= self.page_budget

    def allocate(self, request_id: int,
                 total_len: int) -> Optional[SlotLease]:
        """Lease a slot (+ pages) for a request of ``total_len``
        resident positions, or ``None`` when nothing fits.  The slot's
        device buffer is untouched — see the recycling note above."""
        if not self.can_admit(total_len):
            return None
        slot = self._free_slots.pop()
        lease = SlotLease(slot=slot, pages=self.pages_needed(total_len),
                          request_id=request_id)
        self._leases[slot] = lease
        self.pages_in_use += lease.pages
        return lease

    def release(self, slot: int) -> None:
        """Return a slot's pages to the pool (eviction / completion) —
        no retrace, no device write; the next occupant's prefill and
        the overwrite-before-read decode order make stale K/V
        unobservable."""
        lease = self._leases.pop(slot, None)
        if lease is None:
            return
        self.pages_in_use -= lease.pages
        self._free_slots.append(slot)
        self.total_evictions += 1

    def lease_of(self, slot: int) -> Optional[SlotLease]:
        return self._leases.get(slot)

    @property
    def active_slots(self) -> int:
        return self.n_slots - len(self._free_slots)

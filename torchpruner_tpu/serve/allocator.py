"""Bucketed KV-cache allocation for the serving slot array.

The physical cache is one static ``(n_slots, max_len, H, Dh)`` buffer
per attention layer (generate.init_cache) — static shapes are the TPU
contract, so admission control happens in HOST bookkeeping, not device
reallocation.  This module owns that bookkeeping:

- **Lane-aligned buckets** — page and prefill-bucket sizes come from
  the same 8-sublane / 128-lane alignment ladder ``prune_by_scores``
  rounds kept widths to (core.pruner.bucket_drop, SURVEY.md §7): a
  bounded, hardware-shaped set of compiled prefill lengths means a
  bounded total compile bill, exactly the recompilation-economics
  argument made for prune schedules.
- **Pages** — each slot's ``max_len`` positions are divided into pages
  of ``page_len`` tokens.  A request is admitted only when a free slot
  has enough pages for ``prompt + max_new``; the engine draws down a
  shared page budget so obs can report KV residency
  (``serve_kv_pages_in_use``) and an operator can cap it below
  ``n_slots * pages_per_slot`` (over-subscription guard for mixed
  long/short traffic).
- **Recycling without retrace** — freeing a slot is a host-side list
  append; the device buffer is NOT zeroed.  Stale K/V from the previous
  occupant is harmless by construction: a position ``t`` of a slot's
  cache only becomes attendable once that slot's decode position
  reaches ``t``, and the decode step writes position ``t`` before
  reading it (generate._decode_attention masks ``t > pos``).  The
  ragged-parity tests pin this by poisoning the cache and checking
  bit-identical logits.
- **Prefix sharing** (``prefix_pages > 0``) — a radix trie over
  page-sized token chunks (:class:`PrefixTrie`) indexes a pool of
  published K/V pages.  At admission the prompt's leading WHOLE pages
  are matched against the trie and mapped (copied) into the slot's
  rows instead of re-prefilled; matched trie nodes are PINNED
  (refcounted) for the request's lifetime, and eviction is LRU over
  unpinned leaf nodes only — a pinned node refuses eviction.  The map
  is a copy, never an alias: decode writes land in the slot's private
  rows, so the pool page stays canonical (the "copy-on-write" page is
  materialized at admission time, which is what keeps sharing inside
  the static-shape contract — no page-indirect addressing in the
  compiled programs).  Published K/V are canonical because every
  producer computes them with the SAME chunk-aligned prefill programs
  at the same absolute positions (engine; chunk | page_len), so a
  mapped page is bit-identical to what a private re-prefill would have
  written — the sharing-on/off parity contract.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

#: the TPU tiling ladder shared with core.pruner.bucket_drop: vector
#: lanes are 128 wide, sublanes 8 deep — multiples tile the MXU/VPU
#: cleanly and bound the distinct-shape set
SUBLANE = 8
LANE = 128


def aligned_len(n: int) -> int:
    """Round ``n`` up the lane-alignment ladder: to a multiple of 8
    below 128, to a multiple of 128 above — the same rounding direction
    (up = conservative) as ``bucket_drop``'s kept-width rule."""
    if n <= 0:
        return SUBLANE
    if n <= LANE:
        return -(-n // SUBLANE) * SUBLANE
    return -(-n // LANE) * LANE


def prefill_buckets(max_prompt: int) -> List[int]:
    """The bucketed prefill-length ladder up to ``max_prompt``: every
    aligned length {8, 16, .., 128, 256, ..} — one compiled prefill
    program per bucket actually used, never one per prompt length.
    The LAST bucket is ``max_prompt`` itself (possibly unaligned):
    prefill caches insert into the serving cache's ``max_len`` rows, so
    a bucket may never exceed the physical slot length."""
    out, n = [], SUBLANE
    while n < max_prompt:
        out.append(n)
        n = aligned_len(n + 1)
    out.append(max_prompt)
    return out


def bucket_for(n: int, buckets: List[int]) -> int:
    """Smallest bucket holding ``n`` tokens."""
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"prompt length {n} exceeds the largest prefill "
                     f"bucket {buckets[-1]}")


class PrefixNode:
    """One radix-trie node: an edge label of whole page chunks plus the
    physical pool page holding each chunk's K/V.  ``refcount`` counts
    active requests whose admission match pinned this node; a pinned
    node refuses eviction (its pages may be re-mapped any step)."""

    __slots__ = ("label", "pages", "children", "parent", "refcount",
                 "last_used")

    def __init__(self, label: Tuple[int, ...] = (),
                 pages: Optional[List[int]] = None,
                 parent: Optional["PrefixNode"] = None):
        self.label = tuple(label)
        self.pages: List[int] = list(pages or [])
        #: first-page-chunk -> child
        self.children: Dict[Tuple[int, ...], "PrefixNode"] = {}
        self.parent = parent
        self.refcount = 0
        self.last_used = 0


@dataclass
class PrefixMatch:
    """A pinned admission match: ``tokens`` leading prompt tokens
    (a multiple of ``page_len``) are resident in pool ``pages`` (prompt
    order).  Hold until the request leaves its slot, then release via
    the allocator (unpins the node path exactly once)."""

    tokens: int
    pages: List[int]
    nodes: List[PrefixNode] = field(repr=False)
    #: uncapped resident whole-page tokens (>= ``tokens``) — the delta
    #: is the copy-on-write region the engine re-prefills privately
    available: int = 0
    released: bool = field(default=False, repr=False)


class PrefixTrie:
    """Radix trie over page-sized token chunks (host bookkeeping only —
    it never touches device memory; physical pages are just ints the
    engine's copy programs consume).  Edges are runs of whole page
    chunks; divergence or partial overlap mid-edge SPLITS the edge at a
    page boundary, so every match/insert boundary stays page-aligned."""

    def __init__(self, page_len: int):
        if page_len <= 0:
            raise ValueError(f"page_len must be > 0, got {page_len}")
        self.page_len = int(page_len)
        self.root = PrefixNode()
        self._clock = 0

    # -- helpers -------------------------------------------------------------

    def _chunks(self, ids: Sequence[int],
                n_tokens: int) -> List[Tuple[int, ...]]:
        L = self.page_len
        ids = [int(t) for t in ids[: (n_tokens // L) * L]]
        return [tuple(ids[i:i + L]) for i in range(0, len(ids), L)]

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def nodes(self) -> Iterator[PrefixNode]:
        stack = [self.root]
        while stack:
            n = stack.pop()
            if n is not self.root:
                yield n
            stack.extend(n.children.values())

    @property
    def total_pages(self) -> int:
        return sum(len(n.pages) for n in self.nodes())

    @property
    def shared_pages(self) -> int:
        """Pages on nodes pinned by at least one active request — the
        ``serve_kv_pages_shared`` gauge."""
        return sum(len(n.pages) for n in self.nodes() if n.refcount > 0)

    def _split(self, node: PrefixNode, k_pages: int) -> PrefixNode:
        """Split ``node``'s edge after its first ``k_pages`` chunks:
        a new intermediate node takes the prefix (inheriting the pins —
        every path that pinned the deep node passed through the prefix),
        ``node`` keeps the remainder and its subtree."""
        L = self.page_len
        if not (0 < k_pages < len(node.pages)):
            raise ValueError(f"split point {k_pages} out of range for an "
                             f"edge of {len(node.pages)} page(s)")
        parent = node.parent
        mid = PrefixNode(label=node.label[:k_pages * L],
                         pages=node.pages[:k_pages], parent=parent)
        mid.refcount = node.refcount
        mid.last_used = node.last_used
        node.label = node.label[k_pages * L:]
        node.pages = node.pages[k_pages:]
        node.parent = mid
        mid.children[node.label[:L]] = node
        parent.children[mid.label[:L]] = mid
        return mid

    # -- the three verbs -----------------------------------------------------

    def match(self, ids: Sequence[int], max_tokens: Optional[int] = None
              ) -> Tuple[int, List[int], List[PrefixNode]]:
        """Longest whole-page prefix of ``ids`` (capped at
        ``max_tokens``) resident in the trie: returns ``(tokens, pool
        pages in prompt order, node path)``.  Partial overlap with an
        edge splits it at the last matched page so the path can be
        pinned exactly.  Bumps LRU recency; does NOT pin — callers pin
        via :meth:`pin` once they commit to the mapping."""
        n = len(ids) if max_tokens is None else min(len(ids), max_tokens)
        chunks = self._chunks(ids, n)
        node, i = self.root, 0
        pages: List[int] = []
        path: List[PrefixNode] = []
        now = self._tick()
        while i < len(chunks):
            child = node.children.get(chunks[i])
            if child is None:
                break
            want = chunks[i:i + len(child.pages)]
            have = self._chunks(child.label, len(child.label))
            k = 0
            while k < len(have) and k < len(want) and have[k] == want[k]:
                k += 1
            if k == 0:
                break
            if k < len(child.pages):
                child = self._split(child, k)
            child.last_used = now
            pages.extend(child.pages)
            path.append(child)
            i += k
            node = child
            if k < len(have):
                break
        return len(pages) * self.page_len, pages, path

    def pin(self, nodes: Sequence[PrefixNode]) -> None:
        for n in nodes:
            n.refcount += 1

    def unpin(self, nodes: Sequence[PrefixNode]) -> None:
        for n in nodes:
            if n.refcount <= 0:
                raise RuntimeError(
                    "prefix refcount underflow: unpin without a "
                    "matching pin (double release?)")
            n.refcount -= 1

    def insert(self, ids: Sequence[int], n_tokens: int,
               acquire) -> List[Tuple[int, int]]:
        """Publish the first ``n_tokens`` (rounded DOWN to whole pages)
        of ``ids``: walk the trie, split at any mid-edge divergence, and
        append the novel tail as one compressed edge, calling
        ``acquire(protect_nodes) -> Optional[page_id]`` per new chunk
        (the allocator's pool free-list / LRU eviction hook — the
        current path is passed so eviction can never free a node the
        insert is extending).  Returns ``[(page_index_in_prompt,
        pool_page_id), ...]`` for the chunks the caller must copy into
        the pool; an exhausted pool truncates the publication."""
        chunks = self._chunks(ids, n_tokens)
        node, i = self.root, 0
        now = self._tick()
        while i < len(chunks):
            child = node.children.get(chunks[i])
            if child is None:
                break
            want = chunks[i:i + len(child.pages)]
            have = self._chunks(child.label, len(child.label))
            k = 0
            while k < len(have) and k < len(want) and have[k] == want[k]:
                k += 1
            if k == 0:
                break
            if k < len(child.pages):
                child = self._split(child, k)
            child.last_used = now
            i += k
            node = child
            if k < len(have):
                break
        out: List[Tuple[int, int]] = []
        if i >= len(chunks):
            return out
        L = self.page_len
        fresh = PrefixNode(parent=node)
        protect = [fresh, node] + [a for a in _ancestors(node)]
        for j in range(i, len(chunks)):
            pg = acquire(protect)
            if pg is None:
                break
            fresh.label += chunks[j]
            fresh.pages.append(pg)
            out.append((j, pg))
        if not fresh.pages:
            return out
        fresh.last_used = now
        node.children[fresh.label[:L]] = fresh
        return out

    def evict_lru(self, protect: Sequence[PrefixNode] = ()
                  ) -> List[int]:
        """Free the least-recently-used UNPINNED leaf edge's pages.
        Returns the freed pool page ids — empty when every leaf is
        pinned (the evict-while-shared refusal) or the trie is empty."""
        protect_ids = {id(p) for p in protect}
        victim: Optional[PrefixNode] = None
        for n in self.nodes():
            if n.children or n.refcount > 0 or id(n) in protect_ids:
                continue
            if victim is None or n.last_used < victim.last_used:
                victim = n
        if victim is None:
            return []
        del victim.parent.children[victim.label[:self.page_len]]
        pages, victim.pages = victim.pages, []
        return pages

    def reset(self) -> List[int]:
        """Drop every node (checkpoint hot-swap: pooled K/V computed
        under the old weights is invalid) and return all pages."""
        pages = [p for n in self.nodes() for p in n.pages]
        self.root = PrefixNode()
        return pages


def _ancestors(node: PrefixNode) -> Iterator[PrefixNode]:
    while node is not None and node.parent is not None:
        yield node
        node = node.parent


@dataclass
class SlotLease:
    """One admitted request's residency: which slot, how many pages."""

    slot: int
    pages: int
    request_id: int
    #: pinned prefix-pool mapping (sharing enabled + admission hit) —
    #: released with the slot
    prefix_match: Optional[PrefixMatch] = None
    #: QoS tenant charged for these pages (per-tenant quota accounting)
    tenant: Optional[str] = None


@dataclass
class KVCacheAllocator:
    """Slot + page bookkeeping over the static serving cache (see
    module docstring).  Pure host state — O(n_slots) lists, no device
    handles — so the scheduler can consult it at every step boundary
    for free."""

    n_slots: int
    max_len: int
    page_len: int = 0
    #: optional global page budget (< n_slots * pages_per_slot caps
    #: total KV residency below the physical buffer)
    page_budget: int = 0
    #: prefix-sharing pool size in pages (0 = sharing off); the engine
    #: sizes its device pool buffers from this
    prefix_pages: int = 0
    _free_slots: List[int] = field(default_factory=list)
    _leases: Dict[int, SlotLease] = field(default_factory=dict)
    pages_in_use: int = 0
    total_evictions: int = 0
    # -- prefix-sharing counters (host truth; the engine mirrors them
    # into obs so sharing-off runs emit NO serve_prefix_* scalars) ----
    prefix_hits: int = 0
    prefix_misses: int = 0
    prefix_hit_tokens: int = 0
    prefix_published_pages: int = 0
    prefix_evictions: int = 0
    #: publications truncated because every pool page was pinned/full
    prefix_pool_exhausted: int = 0

    def __post_init__(self):
        if self.page_len <= 0:
            # default page: one lane-aligned chunk, capped at the slot
            self.page_len = min(aligned_len(min(self.max_len, LANE)),
                                self.max_len)
        self.page_len = min(self.page_len, self.max_len)
        self._free_slots = list(range(self.n_slots))[::-1]  # pop() -> 0 first
        if self.page_budget <= 0:
            self.page_budget = self.n_slots * self.pages_per_slot
        self._trie = PrefixTrie(self.page_len)
        self._free_prefix = list(range(self.prefix_pages))[::-1]
        #: tenant -> pages currently leased (QoS quota accounting)
        self._tenant_pages: Dict[str, int] = {}

    @property
    def pages_per_slot(self) -> int:
        return -(-self.max_len // self.page_len)

    def pages_needed(self, total_len: int) -> int:
        return -(-total_len // self.page_len)

    def can_admit(self, total_len: int) -> bool:
        if total_len > self.max_len:
            return False
        need = self.pages_needed(total_len)
        return bool(self._free_slots) and \
            self.pages_in_use + need <= self.page_budget

    def tenant_pages(self, tenant: Optional[str]) -> int:
        """Pages currently leased to ``tenant`` (0 for None/unknown)."""
        if tenant is None:
            return 0
        return self._tenant_pages.get(tenant, 0)

    def exceeds_quota(self, tenant: Optional[str], total_len: int,
                      quota: int) -> bool:
        """Would leasing ``total_len`` push ``tenant`` past its KV-page
        ``quota``?  The quota is the QoS table's per-tenant ceiling
        (``quota <= 0`` disables).  Distinct from :meth:`can_admit`
        (transient global pressure → WAIT): an over-quota admission is
        the tenant's own footprint → SHED, so it never head-of-line
        blocks the other tenants."""
        if tenant is None or quota <= 0:
            return False
        return self.tenant_pages(tenant) \
            + self.pages_needed(total_len) > quota

    def allocate(self, request_id: int, total_len: int,
                 tenant: Optional[str] = None) -> Optional[SlotLease]:
        """Lease a slot (+ pages) for a request of ``total_len``
        resident positions, or ``None`` when nothing fits.  The slot's
        device buffer is untouched — see the recycling note above.
        ``tenant`` charges the pages to a QoS tenant's quota account."""
        if not self.can_admit(total_len):
            return None
        slot = self._free_slots.pop()
        lease = SlotLease(slot=slot, pages=self.pages_needed(total_len),
                          request_id=request_id, tenant=tenant)
        self._leases[slot] = lease
        self.pages_in_use += lease.pages
        if tenant is not None:
            self._tenant_pages[tenant] = \
                self._tenant_pages.get(tenant, 0) + lease.pages
        return lease

    def release(self, slot: int) -> None:
        """Return a slot's pages to the pool (eviction / completion) —
        no retrace, no device write; the next occupant's prefill and
        the overwrite-before-read decode order make stale K/V
        unobservable.  A pinned prefix match is unpinned here, so the
        trie's refcounts track slot residency exactly."""
        lease = self._leases.pop(slot, None)
        if lease is None:
            return
        if lease.prefix_match is not None:
            self.release_prefix(lease.prefix_match)
            lease.prefix_match = None
        self.pages_in_use -= lease.pages
        if lease.tenant is not None:
            left = self._tenant_pages.get(lease.tenant, 0) - lease.pages
            if left > 0:
                self._tenant_pages[lease.tenant] = left
            else:
                self._tenant_pages.pop(lease.tenant, None)
        self._free_slots.append(slot)
        self.total_evictions += 1

    def lease_of(self, slot: int) -> Optional[SlotLease]:
        return self._leases.get(slot)

    @property
    def active_slots(self) -> int:
        return self.n_slots - len(self._free_slots)

    # -- prefix sharing ------------------------------------------------------

    @property
    def prefix_enabled(self) -> bool:
        return self.prefix_pages > 0

    @property
    def shared_pages(self) -> int:
        """Pool pages pinned by at least one resident request."""
        return self._trie.shared_pages if self.prefix_enabled else 0

    @property
    def prefix_pool_used(self) -> int:
        return self.prefix_pages - len(self._free_prefix)

    def match_prefix(self, prompt_ids,
                     max_tokens: Optional[int] = None
                     ) -> Optional[PrefixMatch]:
        """Match (and PIN) the prompt's longest resident whole-page
        prefix.  ``max_tokens`` caps the match — the engine passes
        ``len(prompt) - 1`` so at least one real position is always
        prefilled (the first token's logits must be computed).  Returns
        ``None`` on a miss; a hit must be released exactly once via
        :meth:`release_prefix` (or implicitly by :meth:`release`)."""
        if not self.prefix_enabled:
            return None
        # uncapped probe first: the capped match below may refuse
        # resident pages at the write boundary — that delta is the COW
        # region the engine accounts for
        available, _, _ = self._trie.match(prompt_ids, None)
        tokens, pages, nodes = self._trie.match(prompt_ids, max_tokens)
        if tokens <= 0:
            self.prefix_misses += 1
            return None
        self._trie.pin(nodes)
        self.prefix_hits += 1
        self.prefix_hit_tokens += tokens
        return PrefixMatch(tokens=tokens, pages=pages, nodes=nodes,
                           available=max(available, tokens))

    def release_prefix(self, match: PrefixMatch) -> None:
        """Unpin a match's node chain (idempotent per match object —
        the refcount-never-negative property).  The chain is walked via
        CURRENT parent pointers, not the match-time path: a later
        match/insert may have split a pinned edge, and the split's mid
        node inherited this pin (``PrefixTrie._split``) — releasing the
        stale path would leak that pin and leave the mid's pages
        permanently unevictable."""
        if match.released:
            return
        match.released = True
        self._trie.unpin(list(_ancestors(match.nodes[-1])))

    def publish_prefix(self, prompt_ids,
                       n_tokens: int) -> List[Tuple[int, int]]:
        """Index the first ``n_tokens`` (whole pages) of a freshly
        prefilled prompt, acquiring pool pages from the free list or by
        LRU-evicting unpinned edges.  Returns the ``(page_index,
        pool_page)`` copies the engine must perform."""
        if not self.prefix_enabled:
            return []
        plan = self._trie.insert(prompt_ids, n_tokens, self._acquire_page)
        self.prefix_published_pages += len(plan)
        return plan

    def _acquire_page(self, protect) -> Optional[int]:
        if self._free_prefix:
            return self._free_prefix.pop()
        freed = self._trie.evict_lru(protect)
        if not freed:
            self.prefix_pool_exhausted += 1
            return None
        self.prefix_evictions += len(freed)
        self._free_prefix.extend(freed)
        return self._free_prefix.pop()

    def reset_prefix(self) -> None:
        """Invalidate the whole pool (checkpoint hot-swap: pooled K/V
        belongs to the old weights).  Pins survive on the MATCH objects
        of in-flight requests, but the swap only lands on an empty slot
        array, so by construction nothing is pinned here."""
        if not self.prefix_enabled:
            return
        self._free_prefix.extend(self._trie.reset())

"""Flagship single-chip serving: Llama-3-8B decode via int4 weights.

The BASELINE.json Llama-3-8B config cannot be SERVED on one 16 GB chip
in bf16: 8.0B params × 2 bytes ≈ 15 GB of weights before the KV cache
or a single activation.  int4 weight storage (ops/quant.py bits=4 +
the fused-unpack kernel in ops/int4_matmul.py) shrinks the matmul
weights to ~3.8 GB, leaving room for a bf16 embedding, the KV cache
and activations — the whole 8B model decodes on ONE chip.  Nothing in
the reference framework (a single-device vision pruning library,
SURVEY.md §2) has any serving path at all; this experiment measures
the capability its users would gain by switching.

Measured variants (gen tok/s on the real chip):

- ``int4_dense``: the full 8B config, int4 matmul weights.
- ``int4_pruned``: 25 % of FFN hidden channels pruned (the BASELINE
  prune target — ffn_dim 14336 → 10752), then int4 — the
  prune-then-quantize serving pipeline of examples/04 at 8B scale.
- ``int8_dense``: the full config at int8 (~8.5 GB — also one-chip
  servable); int4 vs int8 at identical FLOPs is the fused-unpack
  kernel's bandwidth claim measured at 8B.

Params are built DIRECTLY at the quantized representation: each float
leaf is created on device in bf16, quantized, and dropped, so peak
transient memory is one leaf (+ its f32 quantize copy, ~2.1 GB for
lm_head) on top of the quantized tree — no 8B master is ever
materialized on host or device.  Weights are random; decode cost is
data-independent (same matmuls, same cache writes every step), so
throughput on random weights equals throughput on trained ones.

Run: ``python -m torchpruner_tpu.experiments.llama8b_decode
[--out results/...json] [--cpu --smoke]``.
"""

from __future__ import annotations

import json
import sys
import time


def quantized_random_params(model, *, bits: int = 4, seed: int = 0,
                            dtype=None):
    """A servable ``(params, state)`` with :class:`QTensor` leaves at
    every site ``quantize_params`` would quantize, built leaf-by-leaf
    on device (see module docstring).  Norm scales init to ones and
    biases to zeros; matmul weights to small normals — values only
    matter for numerics, not for decode throughput."""
    import jax
    import jax.numpy as jnp

    from torchpruner_tpu.core import layers as L
    from torchpruner_tpu.core.segment import init_model
    from torchpruner_tpu.ops.quant import _QUANT_KEYS, quantize_tensor

    dtype = dtype or jnp.bfloat16
    p_shapes, s_shapes = jax.eval_shape(
        lambda: init_model(model, seed, dtype))
    key = jax.random.PRNGKey(seed)

    def build(specs, shapes):
        nonlocal key
        out = {}
        for spec in specs:
            name = spec.name
            if name not in shapes:
                continue
            if isinstance(spec, L.COMPOSITE_TYPES):
                out[name] = build(spec.body + spec.shortcut, shapes[name])
                continue
            qkeys = _QUANT_KEYS.get(type(spec).__name__, {})
            entry = {}
            for pname, sd in shapes[name].items():
                key, sub = jax.random.split(key)
                if pname in ("scale",):
                    leaf = jnp.ones(sd.shape, dtype)
                elif pname.startswith("b"):
                    leaf = jnp.zeros(sd.shape, dtype)
                else:
                    leaf = jax.random.normal(sub, sd.shape, dtype) * 0.02
                if pname in qkeys:
                    entry[pname] = quantize_tensor(
                        leaf, in_axes=qkeys[pname], bits=bits)
                    del leaf  # one transient float leaf at a time
                else:
                    entry[pname] = leaf
            out[name] = entry
        return out

    params = build(model.layers, p_shapes)
    state = jax.tree_util.tree_map(
        lambda sd: jnp.zeros(sd.shape, sd.dtype), s_shapes)
    return params, state


def logical_params(params) -> int:
    """Parameter count at the LOGICAL (unpacked, scale-free) shapes —
    ``param_count`` over a quantized tree would count packed bytes and
    scales as parameters."""
    import math

    import jax

    from torchpruner_tpu.ops.quant import QTensor

    total = 0
    for leaf in jax.tree_util.tree_leaves(
            params, is_leaf=lambda x: isinstance(x, QTensor)):
        total += (math.prod(leaf.shape) if isinstance(leaf, QTensor)
                  else leaf.size)
    return int(total)


def weight_bytes(params) -> int:
    """Bytes of weight traffic per decode step: every leaf is read once
    per token batch, except the embedding table (gathered, B rows)."""
    import jax

    from torchpruner_tpu.ops.quant import QTensor

    total = 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(
            params, is_leaf=lambda x: isinstance(x, QTensor)):
        if any(getattr(k, "key", None) == "emb" for k in path):
            continue
        if isinstance(leaf, QTensor):
            total += leaf.q.size + leaf.scale.size * 4
        else:
            total += leaf.size * leaf.dtype.itemsize
    return int(total)


def measure_decode(model, params, *, batch: int, prompt_len: int,
                   n_new: int, runs: int = 2) -> dict:
    """gen tok/s for one model+params: first call compiles (reported
    separately), then the best of ``runs`` steady calls."""
    import jax.numpy as jnp

    from torchpruner_tpu.generate import generate
    from torchpruner_tpu.utils.profiling import hard_fence

    prompt = jnp.zeros((batch, prompt_len), jnp.int32)

    def once():
        t0 = time.perf_counter()
        toks = generate(model, params, prompt, n_new,
                        cache_dtype=jnp.bfloat16)
        hard_fence(toks)
        return time.perf_counter() - t0

    first = once()
    steady = min(once() for _ in range(runs))
    return {
        "gen_tokens_per_s": round(batch * n_new / steady, 1),
        "ms_per_token_step": round(steady / n_new * 1e3, 3),
        "steady_s": round(steady, 3),
        "first_call_s": round(first, 1),
        "shape": f"B{batch} prompt{prompt_len} new{n_new}",
    }


def run(smoke: bool = False) -> dict:
    import jax

    from torchpruner_tpu.models import llama

    if smoke:
        dims = dict(vocab_size=512, dim=64, depth=2, num_heads=4,
                    num_kv_heads=2, head_dim=16, ffn_dim=128, seq_len=64)
        pruned_ffn = 96
        batch, prompt_len, n_new = 2, 8, 8
    else:
        # Llama-3-8B (BASELINE.json row: vocab 128256, dim 4096,
        # depth 32, 32Q/8KV heads, FFN 14336)
        dims = dict(seq_len=256)
        pruned_ffn = 10752  # 25% FFN channels pruned
        batch, prompt_len, n_new = 8, 64, 64

    out: dict = {
        "platform": jax.devices()[0].platform,
        "device": getattr(jax.devices()[0], "device_kind", ""),
        "variants": {},
    }

    # int8 (~8.5 GB at 8B) also fits one 16 GB chip — measuring it next
    # to int4 IS the fused-unpack kernel's bandwidth claim at 8B scale
    # (int4 reads half the weight bytes per decoded token)
    for tag, bits, ffn in (("int4_dense", 4, None),
                           ("int4_pruned", 4, pruned_ffn),
                           ("int8_dense", 8, None)):
        cfg = dict(dims)
        if ffn is not None:
            cfg["ffn_dim"] = ffn
        model = llama(**cfg)
        t0 = time.perf_counter()
        params, _state = quantized_random_params(model, bits=bits)
        build_s = time.perf_counter() - t0
        wb = weight_bytes(params)
        r = measure_decode(model, params, batch=batch,
                           prompt_len=prompt_len, n_new=n_new)
        r.update({
            "params": logical_params(params),
            "weight_bytes_per_step": wb,
            "weight_gb": round(wb / 1e9, 2),
            "build_s": round(build_s, 1),
            # bytes every decode step must stream from HBM / its time
            "implied_GB_s": round(
                wb / (r["steady_s"] / n_new) / 1e9, 1),
        })
        r["bits"] = bits
        if ffn is not None:
            r["pruned_ffn_fraction"] = 0.25
        out["variants"][tag] = r
        print(f"[llama8b_decode] {tag}: {r}", file=sys.stderr, flush=True)

    d = out["variants"]
    if "int4_dense" in d and "int4_pruned" in d:
        out["prune_decode_speedup"] = round(
            d["int4_pruned"]["gen_tokens_per_s"]
            / d["int4_dense"]["gen_tokens_per_s"], 3)
    if "int4_dense" in d and "int8_dense" in d:
        out["int4_vs_int8_speedup"] = round(
            d["int4_dense"]["gen_tokens_per_s"]
            / d["int8_dense"]["gen_tokens_per_s"], 3)
    return out


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="")
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args(argv)
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    result = run(smoke=args.smoke)
    print(json.dumps(result, indent=1))
    if args.out:
        import os

        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
        print(f"wrote {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()

"""Same-box, same-data, same-weights head-to-head vs the reference.

Every other comparison in this repo is against the reference's COMMITTED
numbers (an unnamed 2020 CUDA GPU).  This experiment runs the actual
reference implementation — ``/root/reference/torchpruner``, imported
as-is on CPU torch — and this framework side by side on identical data
and identical initial weights, through the reference's own headline
recipe ("Pruning Untrained Networks", SURVEY.md §3.4): Shapley
attribution (sv_samples=5) on every prunable layer, outermost first,
prune the negative-score units with cascade, measure accuracy
before/after.  Reported per side: scoring+prune wall-clock, params
before/after, accuracy before/after — plus the per-layer Spearman rank
agreement between the two implementations' scores (same weights, same
data; Monte-Carlo permutations differ, so agreement is statistical, not
exact).

The reference package is executed unmodified as the benchmark target
(read-only: bytecode writing is disabled so importing never touches the
reference tree).  The torch-side model is a minimal torch.nn stack
implementing the reference's ``forward_partial`` protocol at the same
widths (784-2024-2024-10 LeakyReLU, reference experiments/models/
mnist.py:14-35) with weights COPIED from this framework's init — the
same role tests/test_torch_import.py's builders play.

Run: ``python -m torchpruner_tpu.experiments.head_to_head
[--n 200] [--out results/...json] [--smoke]``  (CPU on both sides —
the point is same-box protocol parity; TPU numbers live in bench.py).
"""

from __future__ import annotations

import json
import os
import sys
import time

REFERENCE = os.environ.get("TORCHPRUNER_REFERENCE", "/root/reference")


def _spearman(a, b) -> float:
    import numpy as np

    ra = np.argsort(np.argsort(a)).astype(np.float64)
    rb = np.argsort(np.argsort(b)).astype(np.float64)
    ra -= ra.mean()
    rb -= rb.mean()
    denom = np.sqrt((ra * ra).sum() * (rb * rb).sum())
    return float((ra * rb).sum() / denom) if denom else 0.0


def _build_torch_net(widths, torch):
    """The reference protocol's FC net: ``model.fc`` holds the Linear /
    LeakyReLU children and ``forward_partial(x, from_module, to_module)``
    runs the segment — the convention the reference's Shapley fast path
    consumes (reference attributions.py:70-89)."""
    import torch.nn as nn

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            layers = []
            for i in range(len(widths) - 1):
                layers.append(nn.Linear(widths[i], widths[i + 1]))
                if i < len(widths) - 2:
                    layers.append(nn.LeakyReLU())
            self.fc = nn.Sequential(*layers)

        def forward(self, x):
            return self.fc(x)

        def forward_partial(self, x, from_module=None, to_module=None):
            active = from_module is None
            for child in self.fc.children():
                if active:
                    x = child(x)
                if child is from_module:
                    active = True
                if child is to_module:
                    break
            return x

    return Net()


def run(n: int = 200, smoke: bool = False) -> dict:
    import jax
    import numpy as np

    jax.config.update("jax_platforms", "cpu")  # same-box CPU both sides
    if jax.default_backend() != "cpu":
        # the config update is a silent no-op once a backend is cached —
        # a TPU-jax vs CPU-torch comparison must never publish as
        # "one CPU core each"
        raise RuntimeError(
            "head_to_head needs a fresh process (jax backend is "
            f"{jax.default_backend()!r}, not cpu)")

    import jax.numpy as jnp

    from torchpruner_tpu.attributions import ShapleyAttributionMetric
    from torchpruner_tpu.core.graph import pruning_graph
    from torchpruner_tpu.core.pruner import prune_by_scores
    from torchpruner_tpu.core.segment import init_model
    from torchpruner_tpu.data import load_dataset
    from torchpruner_tpu.models.mlp import fc_net
    from torchpruner_tpu.utils.flops import param_count
    from torchpruner_tpu.utils.losses import cross_entropy_loss

    if not os.path.isdir(os.path.join(REFERENCE, "torchpruner")):
        return {"skipped": f"reference package not found at {REFERENCE}"}
    sys.dont_write_bytecode = True  # never write into the reference tree
    if REFERENCE not in sys.path:
        sys.path.insert(0, REFERENCE)
    import torch
    import torch.nn.functional as tF

    from torchpruner.attributions import (  # noqa: E402 - the reference
        ShapleyAttributionMetric as RefShapley,
    )
    from torchpruner.pruner import Pruner as RefPruner  # noqa: E402

    hidden = (32, 32) if smoke else (2024, 2024)
    if smoke:
        n = 64
    widths = (784,) + hidden + (10,)
    model = fc_net(784, hidden=hidden)
    params, state = init_model(model, seed=0)

    val = load_dataset("mnist_flat", "val", n=n, seed=0)
    test = load_dataset("mnist_flat", "test", n=max(2 * n, 500), seed=0)
    bs = max(n // 2, 1)
    batches = [(jnp.asarray(x), jnp.asarray(y)) for x, y in val.batches(bs)]

    tnet = _build_torch_net(widths, torch).eval()
    linears = [m for m in tnet.fc if isinstance(m, torch.nn.Linear)]
    with torch.no_grad():
        for lin, name in zip(linears, ("fc1", "fc2", "out")):
            lin.weight.copy_(torch.from_numpy(
                np.asarray(params[name]["w"]).T))
            lin.bias.copy_(torch.from_numpy(np.asarray(params[name]["b"])))
    class _Loader(list):
        """DataLoader-shaped batch list: the reference's Shapley fast
        path sizes its row matrix from ``data_gen.dataset``
        (reference shapley_values.py:34)."""

    t_batches = _Loader(
        (torch.from_numpy(x.copy()),
         torch.from_numpy(y.astype(np.int64)))
        for x, y in val.batches(bs))
    t_batches.dataset = range(len(val.x))

    def t_loss(output, target, reduction="mean"):
        return tF.cross_entropy(output, target, reduction=reduction)

    def t_acc(net):
        with torch.no_grad():
            correct = total = 0
            for x, y in test.batches(500):
                pred = net(torch.from_numpy(x)).argmax(1).numpy()
                correct += int((pred == y).sum())
                total += len(y)
        return correct / total

    def j_acc(m, p, s):
        correct = total = 0
        for x, y in test.batches(500):
            out, _ = m.apply(p, jnp.asarray(x), state=s, train=False)
            correct += int((np.asarray(out).argmax(1) == y).sum())
            total += len(y)
        return correct / total

    out: dict = {"n_examples": n, "widths": list(widths),
                 "protocol": "Shapley sv_samples=5, prune negative units, "
                             "outermost layer first (reference 'Pruning "
                             "Untrained Networks' recipe)"}
    out["acc_before"] = {"ours": j_acc(model, params, state),
                         "reference": t_acc(tnet)}

    # ---- ours ----------------------------------------------------------
    m, p, s = model, params, state
    params_before = param_count(p)
    scores_ours: dict = {}
    t0 = time.perf_counter()
    for g in pruning_graph(model)[::-1]:  # outermost first
        # f32 scoring: torch computes f32 on CPU, and bf16 on a CPU
        # backend is EMULATED (slower) — the TPU-side bf16 numbers live
        # in bench.py's mnist_prune leg, not here
        metric = ShapleyAttributionMetric(
            m, p, batches, cross_entropy_loss, state=s, sv_samples=5,
            seed=0)
        scores = metric.run(g.target)
        scores_ours[g.target] = np.asarray(scores)
        res = prune_by_scores(m, p, g.target, scores, policy="negative",
                              state=s)
        m, p, s = res.model, res.params, res.state
    ours_s = time.perf_counter() - t0
    out["ours"] = {
        "seconds": round(ours_s, 2),
        "params": [params_before, param_count(p)],
        "acc_after": j_acc(m, p, s),
    }
    print(f"[head_to_head] ours: {out['ours']}", file=sys.stderr,
          flush=True)

    # ---- reference (unmodified, torch CPU) -----------------------------
    device = torch.device("cpu")
    pruner = RefPruner(tnet, input_size=(widths[0],), device=device)
    tp_before = sum(int(np.prod(q.shape)) for q in tnet.parameters())
    # (module, cascade): outermost prunable first, mirroring the notebook
    plan = [(linears[-2], [linears[-1]]), (linears[0], [linears[1]])]
    scores_ref: dict = {}
    # the reference's Monte-Carlo permutations draw from numpy's GLOBAL
    # rng (reference shapley_values.py:45-47) — seed it so the committed
    # artifact and the smoke test are reproducible
    np.random.seed(0)
    torch.manual_seed(0)
    t0 = time.perf_counter()
    for target_name, (module, cascade) in zip(("fc2", "fc1"), plan):
        metric = RefShapley(tnet, t_batches, t_loss, device, sv_samples=5)
        scores = np.asarray(metric.run(module))
        scores_ref[target_name] = scores
        idx = np.argwhere(scores < 0).flatten()
        pruner.prune_model(module, list(idx), cascading_modules=cascade)
    ref_s = time.perf_counter() - t0
    out["reference"] = {
        "seconds": round(ref_s, 2),
        "params": [tp_before,
                   sum(int(np.prod(q.shape)) for q in tnet.parameters())],
        "acc_after": t_acc(tnet),
    }
    print(f"[head_to_head] reference: {out['reference']}", file=sys.stderr,
          flush=True)

    out["speedup_same_box_cpu"] = round(ref_s / ours_s, 2)
    out["score_spearman"] = {
        k: round(_spearman(scores_ours[k], scores_ref[k]), 3)
        for k in ("fc2", "fc1")
    }
    return out


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=200)
    ap.add_argument("--out", default="")
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args(argv)
    result = run(n=args.n, smoke=args.smoke)
    print(json.dumps(result, indent=1))
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
        print(f"wrote {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()

"""Accuracy-parity experiments on REAL data (BASELINE.md reproduction).

The reference's two headline accuracy results are data-bound, not
synthetic (SURVEY.md §6):

1. "Pruning Untrained Networks" — an *untrained* FC net's test accuracy
   jumps far above chance after pruning every negative-Shapley unit
   (MNIST: 7.16 % → 50.94 %, notebook cells 4/6).
2. The VGG16 layerwise-robustness sweep on a *pretrained* (92.5 %) model,
   summarized as the per-method loss-increase AUC ordering
   (SV mean+2std 0.31 < SV 0.35 < Taylor/Sensitivity/WeightNorm 0.47 <
   Random 0.48 < APoZ 0.56 < Taylor-signed 0.64, notebook cell 11).

This module reruns both protocols end to end on the sklearn **digits**
set — 1,797 real handwritten digit scans bundled with scikit-learn, the
one real dataset available without network egress — and, when the MNIST /
CIFAR-10 distribution files have been prepared into
``TORCHPRUNER_TPU_DATA_DIR`` (see data/prepare.py), on the reference's
exact datasets with the same code path.  ``python -m
torchpruner_tpu.experiments.parity`` runs everything it has data for and
writes the ours-vs-reference table to ``PARITY.md``.
"""

from __future__ import annotations

import os
import time
from typing import Dict, Optional

import numpy as np

from torchpruner_tpu.data import load_dataset
from torchpruner_tpu.train.loop import evaluate
from torchpruner_tpu.utils.config import ExperimentConfig


def _have_real(name: str) -> bool:
    """True when {name} resolves to REAL data (digits when sklearn can
    actually serve it; others when the npy drop-in exists).  Must never
    return True for a synthetic fallback — PARITY.md claims real-data
    reproduction."""
    data_dir = os.environ.get("TORCHPRUNER_TPU_DATA_DIR", "")
    if bool(data_dir) and os.path.exists(
        os.path.join(data_dir, f"{name}_train_x.npy")
    ):
        return True
    if name.startswith("digits"):
        import importlib.util

        return importlib.util.find_spec("sklearn") is not None
    return False


def run_untrained_prune_parity(
    model_name: str = "digits_fc",
    dataset: str = "digits_flat",
    *,
    sv_samples: int = 5,
    seed: int = 0,
    verbose: bool = True,
) -> Dict[str, float]:
    """Reference "Pruning Untrained Networks" protocol on real data:
    score an UNTRAINED net with Shapley on the validation split, prune all
    negative-attribution units outermost-first, report test accuracy
    before/after and the parameter reduction."""
    from torchpruner_tpu.core.segment import init_model
    from torchpruner_tpu.experiments.prune_retrain import (
        MODEL_REGISTRY,
        run_prune_retrain,
    )
    from torchpruner_tpu.utils.flops import param_count

    p0, _ = init_model(MODEL_REGISTRY[model_name][0](), seed=seed)
    params_before = param_count(p0)
    cfg = ExperimentConfig(
        name=f"parity_untrained_{dataset}",
        model=model_name,
        dataset=dataset,
        method="shapley",
        method_kwargs={"sv_samples": sv_samples},
        policy="negative",
        prune_order="reverse",
        score_examples=1000,
        seed=seed,
        log_path="logs/parity.csv",
    )
    t0 = time.perf_counter()
    records = run_prune_retrain(cfg, verbose=verbose)
    elapsed = time.perf_counter() - t0
    out = {
        "dataset": dataset,
        "acc_before": records[0].pre_acc,
        "acc_after": records[-1].post_acc,
        "params_before": params_before,
        "params_after": records[-1].n_params,
        "prune_seconds": round(elapsed, 2),
    }
    if verbose:
        print(
            f"[parity] untrained {dataset}: acc "
            f"{out['acc_before']:.4f} -> {out['acc_after']:.4f}, params "
            f"{out['params_before']} -> {out['params_after']} "
            f"({elapsed:.1f}s)",
            flush=True,
        )
    return out


def train_reference_model(
    model_name: str,
    dataset: str,
    *,
    epochs: int,
    lr: float = 0.05,
    seed: int = 0,
    checkpoint_path: str = "",
    verbose: bool = True,
):
    """Train a model-zoo entry on real data with the reference's recipe
    (SGD + momentum + weight decay + MultiStepLR, reference
    cifar10.py:94-99).  Returns ``(trainer, history)``."""
    from torchpruner_tpu.experiments.train_model import run_train

    milestones = tuple(
        int(epochs * f) for f in (0.4, 0.65, 0.85) if int(epochs * f) > 0
    )
    cfg = ExperimentConfig(
        name=f"parity_train_{model_name}",
        model=model_name,
        dataset=dataset,
        experiment="train",
        epochs=epochs,
        batch_size=64,
        lr=lr,
        momentum=0.9,
        weight_decay=5e-4,
        lr_schedule="multistep" if milestones else "constant",
        lr_milestones=milestones or (10**9,),
        seed=seed,
        checkpoint_path=checkpoint_path,
        log_path="logs/parity.csv",
    )
    return run_train(cfg, verbose=verbose)


def run_trained_robustness_parity(
    model_name: str = "digits_fc",
    dataset: str = "digits_flat",
    *,
    epochs: int = 30,
    sv_samples: int = 5,
    score_examples: int = 300,
    seeds=(0, 1, 2),
    verbose: bool = True,
) -> Dict[str, object]:
    """Reference VGG-notebook protocol at digits scale: train the model on
    real data, then run the full 8-method layerwise-robustness panel on
    the TRAINED weights and report the per-method AUC ordering.

    Runs the whole protocol once per entry in ``seeds`` (fresh training
    AND fresh metric randomness each time) and reports mean ± std across
    seeds — the spread the reference's 3-run protocol reports, extended
    to also cover trained-model variation, so ordering disagreements can
    be attributed to noise or to a real effect."""
    from torchpruner_tpu.experiments.robustness import run_robustness_config

    per_seed_aucs = []
    per_seed_acc = []
    per_seed_loss = []
    for seed in seeds:
        trainer, history = train_reference_model(
            model_name, dataset, epochs=epochs, seed=seed, verbose=verbose
        )
        test = load_dataset(dataset, "test")
        test_loss, test_acc = evaluate(
            trainer.model, trainer.params, trainer.state,
            test.batches(250), trainer.loss_fn,
        )
        cfg = ExperimentConfig(
            name=f"parity_robustness_{dataset}",
            model=model_name,
            dataset=dataset,
            experiment="robustness",
            method="all",
            method_kwargs={"sv_samples": sv_samples},
            score_examples=score_examples,
            seed=seed,
            log_path="logs/parity.csv",
        )
        aucs = run_robustness_config(
            cfg, model=trainer.model, params=trainer.params,
            state=trainer.state, verbose=verbose,
        )
        per_seed_aucs.append({k: float(v) for k, v in aucs.items()})
        per_seed_acc.append(float(test_acc))
        per_seed_loss.append(float(test_loss))
        if verbose:
            order = sorted(aucs, key=aucs.get)
            print(f"[parity] trained {model_name} seed {seed} test acc "
                  f"{test_acc:.4f}; AUC order {order}", flush=True)
    methods = list(per_seed_aucs[0])
    mean = {m: float(np.mean([a[m] for a in per_seed_aucs]))
            for m in methods}
    std = {m: float(np.std([a[m] for a in per_seed_aucs]))
           for m in methods}
    return {
        "dataset": dataset,
        "model": model_name,
        "test_acc": float(np.mean(per_seed_acc)),
        "test_acc_std": float(np.std(per_seed_acc)),
        "test_loss": float(np.mean(per_seed_loss)),
        "epochs": epochs,
        "seeds": list(seeds),
        "aucs": mean,
        "auc_std": std,
        "per_seed_aucs": per_seed_aucs,
    }


REFERENCE_NUMBERS = {
    # BASELINE.md, reference notebook outputs (CUDA GPU, 2020)
    "untrained_mnist": {"acc_before": 0.0716, "acc_after": 0.5094,
                        "params_before": 5_707_690,
                        "params_after": 2_421_737, "prune_seconds": 28.0},
    "untrained_cifar10": {"acc_before": 0.1099, "acc_after": 0.1989,
                          "params_before": 10_338_602,
                          "params_after": 5_079_077, "prune_seconds": 33.5},
    "vgg16_test_acc": 0.925,
    "auc_order": ["sv_mean+2std", "sv", "taylor", "sensitivity",
                  "weight_norm", "random", "apoz", "taylor_signed"],
}


def write_parity_report(
    path: str = "PARITY.md",
    *,
    untrained: Optional[Dict[str, Dict]] = None,
    robustness=None,
) -> str:
    """Render PARITY.md from experiment outputs (see ``main``).
    ``robustness`` is one trained-sweep result dict or a list of them
    (one section per model family — FC and conv+BN)."""
    lines = [
        "# PARITY — ours vs the reference's real-data numbers",
        "",
        "Reference numbers are the committed notebook outputs "
        "(BASELINE.md; CUDA GPU). Ours run on the hardware named per "
        "row. The always-available real dataset in this environment is "
        "sklearn **digits** (1,797 real handwritten 8x8 scans; no "
        "network egress for MNIST/CIFAR downloads) — MNIST/CIFAR rows "
        "appear when `data/prepare.py` has been run on the distribution "
        "files.",
        "",
        "## 1. Pruning untrained networks (Shapley, negative-unit policy)",
        "",
        "| run | acc before | acc after | params before | params after "
        "| prune wall-clock |",
        "|---|---|---|---|---|---|",
    ]
    for key, label in (("untrained_mnist", "reference MNIST-FC (GPU)"),
                       ("untrained_cifar10", "reference CIFAR10-FC (GPU)")):
        r = REFERENCE_NUMBERS[key]
        lines.append(
            f"| {label} | {r['acc_before']:.2%} | {r['acc_after']:.2%} | "
            f"{r['params_before']:,} | {r['params_after']:,} | "
            f"{r['prune_seconds']} s |"
        )
    for name, r in (untrained or {}).items():
        lines.append(
            f"| ours {name} | {r['acc_before']:.2%} | "
            f"{r['acc_after']:.2%} | {r['params_before']:,} | "
            f"{r['params_after']:,} | {r['prune_seconds']} s |"
        )
    lines += [
        "",
        "The phenomenon the reference demonstrates — an untrained net's "
        "accuracy rising far above chance purely by removing "
        "negative-Shapley units — reproduces on real data.",
        "",
        "## 2. Method-ranking AUC on a trained model",
        "",
        f"Reference (pretrained "
        f"{REFERENCE_NUMBERS['vgg16_test_acc']:.1%} VGG16, 15 layers), "
        "AUC order best→worst (lower = better ranking): "
        + " < ".join(f"`{m}`" for m in REFERENCE_NUMBERS["auc_order"])
        + " (0.31 / 0.35 / 0.47 / 0.47 / 0.47 / 0.48 / 0.56 / 0.64).",
        "",
    ]
    if robustness and isinstance(robustness, dict):
        robustness = [robustness]
    for rob in robustness or []:
        aucs = rob["aucs"]
        stds = rob.get("auc_std") or {}
        seeds = rob.get("seeds") or [0]
        order = sorted(aucs, key=aucs.get)
        acc_txt = f"{rob['test_acc']:.2%}"
        if rob.get("test_acc_std") is not None and len(seeds) > 1:
            acc_txt += f" ± {rob['test_acc_std']:.2%}"
        lines += [
            f"Ours ({rob['model']} trained {rob['epochs']} "
            f"epochs on real {rob['dataset']}, test acc {acc_txt}, "
            f"{len(seeds)} seed{'s' if len(seeds) != 1 else ''}):",
            "",
            "| method | AUC (loss increase/unit), mean ± std over seeds |",
            "|---|---|",
        ]
        lines += [
            f"| {m} | {aucs[m]:.4f}"
            + (f" ± {stds[m]:.4f}" if m in stds and len(seeds) > 1 else "")
            + " |"
            for m in order
        ]
        best, worst = order[0], order[-1]
        agree_best = best in ("sv", "sv_mean+2std")
        agree_worst = worst == "taylor_signed"
        ref_order = REFERENCE_NUMBERS["auc_order"]
        n_match = sum(a == b for a, b in zip(order, ref_order))
        lines += [
            "",
            f"Best method: `{best}`"
            + (" (agrees with the reference: an SV variant ranks first)"
               if agree_best else
               " (the reference ranks an SV variant first)")
            + f"; worst: `{worst}`"
            + (" (agrees with the reference)" if agree_worst else "")
            + f". Position-for-position, the ordering matches the "
            + f"reference's 8-method ranking in {n_match} of 8 places.",
            "",
        ]
        if stds and len(seeds) > 1:
            # adjacent pairs whose mean gap is inside one combined std
            # cannot be ordered at this sample size — name them, so
            # mid-table position swaps vs the reference are attributable
            unresolved = [
                (a, b) for a, b in zip(order, order[1:])
                if abs(aucs[a] - aucs[b]) <= stds[a] + stds[b]
            ]
            if unresolved:
                pairs = ", ".join(f"`{a}`~`{b}`" for a, b in unresolved)
                lines += [
                    f"Seed spread: {len(unresolved)} of 7 adjacent pairs "
                    f"in this ordering are separated by less than one "
                    f"combined standard deviation ({pairs}) — positions "
                    f"inside those clusters are statistical ties, the "
                    f"same situation as the reference's own mid-table "
                    f"(taylor/sensitivity/weight_norm/random at "
                    f"0.47/0.47/0.47/0.48).",
                    "",
                ]
            else:
                lines += [
                    "Seed spread: every adjacent pair is separated by "
                    "more than one combined standard deviation — the "
                    "ordering above is stable across seeds.",
                    "",
                ]
    lines += [
        "",
        "## 3. Reproducing the exact MNIST / CIFAR-10 / VGG16 rows",
        "",
        "The code path is identical — only the arrays change. With the "
        "public distribution files on disk:",
        "",
        "```bash",
        "export TORCHPRUNER_TPU_DATA_DIR=/data/torchpruner",
        "python -m torchpruner_tpu.data.prepare mnist   --src /downloads/mnist_idx",
        "python -m torchpruner_tpu.data.prepare cifar10 --src /downloads/cifar-10-batches-py",
        "# untrained-net pruning on real MNIST (reference: 7.16% -> 50.94%)",
        "python -m torchpruner_tpu.experiments.parity --untrained mnist_fc:mnist_flat",
        "# train VGG16-bn with the reference recipe, then the AUC sweep",
        "python -m torchpruner_tpu.experiments.parity --robustness vgg16_bn:cifar10 --epochs 160",
        "```",
        "",
        "Holders of the reference's pretrained checkpoint (the 92.5% "
        "`cifar10_vgg16_bn.pt` its notebook downloads) can skip the "
        "training step entirely: "
        "`tp.import_torch_vgg16_bn(torch.load(path))` maps it onto this "
        "framework's `(model, params, state)` (forward-parity tested "
        "against torch), and `run_robustness_config(cfg, model=..., "
        "params=..., state=...)` runs the sweep on those exact weights.",
        "",
    ]
    text = "\n".join(lines)
    with open(path, "w") as f:
        f.write(text)
    return text


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--untrained", action="append", default=[],
                    help="model:dataset for the untrained-prune protocol "
                    "(default: digits_fc:digits_flat + any prepared real "
                    "sets)")
    ap.add_argument("--robustness", action="append", default=[],
                    help="model:dataset for the trained AUC sweep; repeat "
                    "for several (default: digits FC + digits conv+BN)")
    ap.add_argument("--epochs", type=int, default=30)
    ap.add_argument("--seeds", type=int, default=3,
                    help="number of independent train+sweep repetitions "
                    "per robustness row (mean ± std; reference reports "
                    "3-run spreads)")
    ap.add_argument("--out", default="PARITY.md")
    ap.add_argument("--skip-robustness", action="store_true")
    ap.add_argument("--all", action="store_true",
                    help="every row the available data supports: digits "
                    "rows always; real MNIST/CIFAR-10 untrained rows and "
                    "the VGG16-bn/CIFAR-10 sweep when prepared data is "
                    "found in TORCHPRUNER_TPU_DATA_DIR — the one command "
                    "that emits the reference-complete PARITY.md once "
                    "the distribution files appear")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (a hung TPU tunnel "
                    "otherwise parks backend init indefinitely)")
    args = ap.parse_args(argv)

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    # default rows: the MNIST-FC analog on 64-d real scans AND the
    # CIFAR10-FC analog (the reference's second untrained row) on the
    # same scans at CIFAR-10 geometry (3072-d) — the exact architecture,
    # params exactly the reference's 10,338,602
    runs = args.untrained or [
        "digits_fc:digits_flat", "cifar10_fc:digits32_flat"
    ]
    if not args.untrained:
        for m, d in (("mnist_fc", "mnist_flat"), ("cifar10_fc", "cifar10_flat")):
            if _have_real(d):
                runs.append(f"{m}:{d}")
    untrained = {}
    for spec in runs:
        m, d = spec.split(":")
        if not _have_real(d):
            print(f"[parity] skipping {spec}: no real data", flush=True)
            continue
        untrained[f"{m} on {d}"] = run_untrained_prune_parity(m, d)

    robustness = []
    if not args.skip_robustness:
        specs = args.robustness or [
            "digits_fc:digits_flat", "digits_convnet:digits"
        ]
        if args.all and not args.robustness and _have_real("cifar10"):
            # the reference's exact experiment, with its training recipe
            specs.append("vgg16_bn:cifar10")
        for spec in specs:
            m, d = spec.split(":")
            if _have_real(d):
                robustness.append(run_trained_robustness_parity(
                    m, d, epochs=args.epochs,
                    seeds=tuple(range(args.seeds)),
                ))
    write_parity_report(args.out, untrained=untrained, robustness=robustness)
    print(f"wrote {args.out}", flush=True)


if __name__ == "__main__":
    main()

"""How does the robustness sweep's wall-clock scale with eval-set size?

The bench headline compares our digits32 sweep (300 test examples — the
whole digits test split) against the reference's 6.5 h on 1000 CIFAR-10
examples by scaling wall-clock linearly in example count
(``examples_adjusted_s``).  This experiment MEASURES that scaling on one
layer's full 14-run method panel at n ∈ {75, 150, 300, 1000} — the 1000
row (the reference's own eval count) built by resampling the 300-example
split with replacement, since wall-clock depends on array sizes, not
label novelty.  If cost grows linearly or slower, the adjustment is
conservative (the ablation walks batch over examples, so larger eval
sets amortize fixed per-unit work — sublinear is the expectation on an
MXU).

Writes ``{"rows": [{n, panel_seconds, per_n_ratio}, ...], "base_n",
"verdict"}``; ``per_n_ratio`` is panel_seconds normalized by
(n/base_n) relative to the LARGEST measured row (``base_n``, now 1000;
round-4 artifacts used base_n=300 — renormalize by the ratio of bases
when comparing across rounds).  Ratios ≥ 1 at the SMALLER sizes mean
cost is concave in n (fixed per-panel work amortizes), so the linear
example-count adjustment is an upper bound on the true cost at the
headline's n.

Run: ``python -m torchpruner_tpu.experiments.sweep_scaling
[--layer conv8] [--out results/...json] [--cpu --smoke]``.
"""

from __future__ import annotations

import json
import sys
import time


def run(layer: str = "conv8", sizes=(75, 150, 300, 1000),
        smoke: bool = False, capture: bool = True) -> dict:
    import jax
    import jax.numpy as jnp
    import optax

    from torchpruner_tpu.data import load_dataset
    from torchpruner_tpu.experiments.robustness import (
        layerwise_robustness,
        method_panel,
    )
    from torchpruner_tpu.models import vgg16_bn
    from torchpruner_tpu.train.loop import Trainer
    from torchpruner_tpu.utils.losses import cross_entropy_loss

    if smoke:
        model = vgg16_bn(width_multiplier=0.125, classifier_width=64)
        sizes, epochs, train_bs = (16, 32), 1, 64
    else:
        model = vgg16_bn()
        epochs, train_bs = 12, 128

    train = load_dataset("digits32", "train", seed=0)
    trainer = Trainer.create(model, optax.adam(1e-3), cross_entropy_loss,
                             seed=0, compute_dtype=jnp.bfloat16)
    for epoch in range(epochs):
        for x, y in train.iter_batches(train_bs, shuffle=True, seed=epoch,
                                       drop_remainder=True):
            trainer.step(jnp.asarray(x), jnp.asarray(y))
    params, state = trainer.params, trainer.state

    rows = []
    for n in sizes:
        test = load_dataset("digits32", "test", seed=0)
        if n > len(test.x):
            # grow past the real split size by resampling with
            # replacement: the cost curve depends on array sizes only,
            # and n=1000 is the reference's CIFAR-10 eval count — this
            # row turns the linear example-count adjustment at the
            # headline's n from an extrapolation into a measurement
            test = test.resample(n, seed=0)
        else:
            test = test.subset(n, seed=0)
        batches = [(jnp.asarray(x), jnp.asarray(y))
                   for x, y in test.batches(n)]
        # the bench leg's exact panel (ONE shared definition) on this
        # eval-set size
        methods = method_panel(model, params, batches, cross_entropy_loss,
                               state=state, compute_dtype=jnp.bfloat16)
        t0 = time.perf_counter()
        layerwise_robustness(
            model, params, state, batches, methods, cross_entropy_loss,
            layers=[layer], verbose=False,
            # the headline leg's configuration, bf16 ablation walks
            # included (bench.py vgg16_robustness) — the calibration must
            # measure the cost curve it calibrates.  capture defaults on
            # (the one-pass engine the leg runs); --no-capture A/Bs the
            # O(L²) prefix-recompute path this experiment used to time
            compute_dtype=jnp.bfloat16, capture=capture,
        )
        rows.append({"n": n, "panel_seconds":
                     round(time.perf_counter() - t0, 2)})
        print(f"[sweep_scaling] n={n}: {rows[-1]['panel_seconds']} s",
              file=sys.stderr, flush=True)

    base = rows[-1]
    for r in rows:
        # cost relative to linear scaling from the largest size: <= 1
        # means linear extrapolation OVERestimates the cost at this n
        r["per_n_ratio"] = round(
            (r["panel_seconds"] / base["panel_seconds"])
            / (r["n"] / base["n"]), 3)
    concave = all(r["per_n_ratio"] >= 0.999 for r in rows[:-1])
    return {
        "layer": layer,
        "platform": jax.devices()[0].platform,
        "device": getattr(jax.devices()[0], "device_kind", ""),
        "capture": capture,
        "rows": rows,
        "base_n": base["n"],
        "verdict": (
            "concave in n over the measured range (fixed per-panel "
            "cost amortizes: per_n_ratio >= 1 at smaller n): the "
            "linear example-count adjustment is an upper bound on our "
            f"cost everywhere up to the measured n={rows[-1]['n']}"
            if concave else
            "convex in n at the measured sizes: the linearly-adjusted "
            "headline may understate the cost — do not quote it "
            "without this caveat"),
    }


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--layer", default="conv8")
    ap.add_argument("--out", default="")
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--no-capture", action="store_true",
                    help="disable the one-pass capture engine (A/B the "
                         "per-method prefix-recompute path)")
    args = ap.parse_args(argv)
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    result = run(args.layer, smoke=args.smoke,
                 capture=not args.no_capture)
    print(json.dumps(result, indent=1))
    if args.out:
        import os

        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
        print(f"wrote {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()

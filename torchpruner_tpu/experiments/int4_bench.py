"""Weight-precision decode-matmul bandwidth: bf16 vs int8 (XLA-fused
and kernel-fused) vs fused int4.

The serving lever is BYTES READ per decoded token (PERF.md); this
experiment measures the three weight formats' per-iteration DEVICE time
for the decode-shaped matmul ``(B, D) @ (D, F)`` — an on-device
``fori_loop`` with a data dependency between iterations, timed off the
profiler's XLA-Ops track, because on the tunnelled single chip both
per-call stopwatches (≥ one RTT per call) and loop wall-clock (one RTT
per fence, ~500 µs/iter at N=200) drown microsecond kernels.

Writes ``{"paths": {bf16|int8|int8_kernel|int4_kernel: {device_us,
eff_GB_s}}}``; ``eff_GB_s`` = weight bytes that format reads per
iteration / device time — the bandwidth actually saved.  ``int8`` is
the XLA convert-into-dot formulation (fusion hoped for), ``int8_kernel``
and ``int4_kernel`` the fused dequant Pallas kernel
(ops/fused_matmul.py: integer bytes to VMEM, widen/unpack in-register,
scale fused onto the output block — fusion guaranteed); the XLA-vs-
kernel int8 delta is exactly the "did the convert fuse" question the
old stale-evidence note left open.

Run: ``python -m torchpruner_tpu.experiments.int4_bench
[--out results/...json] [--cpu --smoke]``.
"""

from __future__ import annotations

import json
import sys


def run(smoke: bool = False) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from torchpruner_tpu.ops.fused_matmul import dequant_matmul
    from torchpruner_tpu.ops.int4_matmul import quantize_int4
    from torchpruner_tpu.ops.quant import quantize_tensor
    from torchpruner_tpu.utils import profiling
    from torchpruner_tpu.utils.trace_analysis import summarize_trace

    B, D, F = (4, 256, 256) if smoke else (8, 4096, 4096)
    N = 4 if smoke else 100
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(D, F)).astype(np.float32))
    x = jnp.asarray(rng.normal(size=(B, D)).astype(np.float32))
    wb = w.astype(jnp.bfloat16)
    qt = quantize_tensor(w, in_axes=1)  # the serving int8 formulation
    q8, s8 = qt.q, qt.out_scale().astype(jnp.float32)
    p4, s4 = quantize_int4(w)

    def looped(matmul, *wargs):
        def body(i, c):
            y = matmul(c, *wargs)
            # feed the output back (D == F here) with magnitude pinned,
            # so no iteration can be dead-code-eliminated or reordered
            return (y / (jnp.sqrt(jnp.mean(y * y)) + 1e-6)).astype(x.dtype)

        return jax.jit(lambda x0: jax.lax.fori_loop(0, N, body, x0))

    paths = {
        "bf16": (looped(lambda c, w_: jnp.dot(
            c.astype(jnp.bfloat16), w_,
            preferred_element_type=jnp.float32), wb), D * F * 2),
        "int8": (looped(lambda c, q, s: jnp.dot(
            c.astype(jnp.bfloat16), q.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32) * s[None], q8, s8), D * F),
        "int8_kernel": (looped(
            lambda c, q, s: dequant_matmul(c, q, s, bits=8), q8, s8),
            D * F),
        "int4_kernel": (looped(
            lambda c, p, s: dequant_matmul(c, p, s, bits=4), p4, s4),
            D * F // 2),
    }

    out: dict = {"B": B, "D": D, "F": F, "iters": N,
                 "platform": jax.devices()[0].platform,
                 "device": getattr(jax.devices()[0], "device_kind", ""),
                 "paths": {}}

    def run_paths(tag_suffix, x0, dest):
        for name, (fn, nbytes) in paths.items():
            profiling.hard_fence(fn(x0))  # compile + warm outside trace
            trace_dir = f"logs/int4_bench/{name}{tag_suffix}"
            with profiling.trace(trace_dir):
                profiling.hard_fence(fn(x0))
            dev_s = summarize_trace(trace_dir)["total_ms"] / 1e3 / N
            dest[name] = {
                "device_us": round(dev_s * 1e6, 2),
                "eff_GB_s": (round(nbytes / dev_s / 1e9, 1)
                             if dev_s else None),
            }
            print(f"[int4_bench] {name}{tag_suffix}: {dest[name]}",
                  file=sys.stderr, flush=True)

    run_paths("", x, out["paths"])
    b16 = out["paths"]["bf16"]["device_us"]
    i4 = out["paths"]["int4_kernel"]["device_us"]
    if b16 and i4:
        out["int4_vs_bf16_speedup"] = round(b16 / i4, 3)

    # prefill-shaped rows: the row-TILED kernel grid (rows > 1024 get
    # their own grid dimension — ops/int4_matmul._pick_row_block); here
    # the matmul is MXU-bound, not weight-bandwidth-bound, so the point
    # is that the kernel stays competitive, not that it wins
    Bp = 16 if smoke else 4096
    xp = jnp.asarray(rng.normal(size=(Bp, D)).astype(np.float32))
    out["prefill"] = {"B": Bp, "paths": {}}
    run_paths("_prefill", xp, out["prefill"]["paths"])
    return out


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="")
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args(argv)
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    result = run(smoke=args.smoke)
    print(json.dumps(result, indent=1))
    if args.out:
        import os

        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
        print(f"wrote {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()

"""Recompilation economics of the prune-retrain loop, measured.

Every prune step changes static shapes, so the train step (and any
data-dependent scorer) retraces and recompiles — the XLA-honest cost of
the reference's "on-the-fly" in-place surgery (SURVEY.md §7 "hard
parts").  Two mitigations exist in the framework: width **bucketing**
(``bucket=128`` snaps kept widths to multiples, collapsing the space of
distinct shapes) and the **persistent compilation cache** (repeat shapes
skip compilation across processes).  This experiment measures both:

  schedule = N prune steps on VGG16-bn (taylor scoring, fraction prune,
  a few retrain steps per stage), run under 4 conditions:
  {bucket=1, bucket=128} × {cold cache, warm cache}

Each condition runs in a FRESH subprocess (in-process jit caching would
fake the warm numbers); cold points the persistent cache at a fresh
directory, warm re-runs the identical schedule against the directory the
cold run just filled.  Per step we record the first train-step call
(compile + run) vs the steady-state step, so the "compile bill"
Σ(first − steady) and total schedule wall-clock are both reported.

Run on TPU: ``python -m torchpruner_tpu.experiments.compile_economics
[--steps 5] [--out logs/compile_economics.json]``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time


def run_schedule(bucket: int, steps: int, smoke: bool) -> dict:
    """One prune-retrain schedule in THIS process; returns per-step
    timings.  Invoked by the orchestrator in a fresh subprocess per
    condition."""
    import jax
    import jax.numpy as jnp
    import optax

    from torchpruner_tpu.attributions import TaylorAttributionMetric
    from torchpruner_tpu.core.graph import pruning_graph
    from torchpruner_tpu.core.pruner import prune_by_scores
    from torchpruner_tpu.data import load_dataset
    from torchpruner_tpu.models import vgg16_bn
    from torchpruner_tpu.train.loop import Trainer
    from torchpruner_tpu.utils.losses import cross_entropy_loss
    from torchpruner_tpu.utils.profiling import hard_fence

    if smoke:
        model = vgg16_bn(width_multiplier=0.125, classifier_width=64)
        batch, score_n = 16, 64
    else:
        model = vgg16_bn()
        batch, score_n = 256, 256
    train = load_dataset("digits32", "train", seed=0)
    xb, yb = next(iter(train.iter_batches(batch)))
    x, y = jnp.asarray(xb), jnp.asarray(yb)
    score = load_dataset("digits32", "val", n=score_n, seed=0)
    score_batches = [(jnp.asarray(a), jnp.asarray(b))
                     for a, b in score.batches(score_n)]

    trainer = Trainer.create(model, optax.adam(1e-3), cross_entropy_loss,
                             seed=0, compute_dtype=jnp.bfloat16)
    # prune the wide conv stack back-to-front, the reference's order
    targets = [g.target for g in pruning_graph(model)][::-1]
    records = []
    t_sched = time.perf_counter()
    for i in range(steps):
        target = targets[i % len(targets)]
        t0 = time.perf_counter()
        trainer.step(x, y)
        hard_fence(trainer.params)
        first_s = time.perf_counter() - t0
        steady = []
        for _ in range(3):
            t0 = time.perf_counter()
            trainer.step(x, y)
            hard_fence(trainer.params)
            steady.append(time.perf_counter() - t0)
        steady_s = min(steady)

        t0 = time.perf_counter()
        metric = TaylorAttributionMetric(
            trainer.model, trainer.params, score_batches,
            cross_entropy_loss, state=trainer.state,
            compute_dtype=jnp.bfloat16,
        )
        scores = metric.run(target)
        score_s = time.perf_counter() - t0
        width_before = len(scores)
        res = prune_by_scores(
            trainer.model, trainer.params, target, scores,
            policy="fraction", fraction=0.15, bucket=bucket,
            state=trainer.state, opt_state=trainer.opt_state,
        )
        trainer = trainer.rebuild(res.model, res.params, res.state,
                                  res.opt_state)
        records.append({
            "step": i,
            "target": target,
            "width": f"{width_before}->{res.model.widths().get(target)}",
            "train_first_s": round(first_s, 3),
            "train_steady_s": round(steady_s, 4),
            "train_compile_s": round(max(first_s - steady_s, 0.0), 3),
            "score_first_s": round(score_s, 3),
        })
        print(f"[compile_econ] bucket={bucket} step {i}: "
              f"compile {records[-1]['train_compile_s']}s "
              f"steady {steady_s * 1e3:.1f}ms", file=sys.stderr, flush=True)
    return {
        "bucket": bucket,
        "steps": steps,
        "schedule_wall_s": round(time.perf_counter() - t_sched, 2),
        "train_compile_bill_s": round(
            sum(r["train_compile_s"] for r in records), 2),
        "per_step": records,
    }


def orchestrate(steps: int, smoke: bool, out_path: str) -> dict:
    conditions = []
    base = tempfile.mkdtemp(prefix="compile_econ_cache_")
    for bucket in (1, 128):
        cache_dir = os.path.join(base, f"bucket{bucket}")
        for phase in ("cold", "warm"):
            cmd = [
                sys.executable, "-m",
                "torchpruner_tpu.experiments.compile_economics",
                "--run-one", "--bucket", str(bucket),
                "--steps", str(steps), "--cache-dir", cache_dir,
            ]
            if smoke:
                cmd += ["--smoke", "--cpu"]
            t0 = time.perf_counter()
            cell = {"bucket": bucket, "cache": phase}
            try:
                proc = subprocess.run(cmd, capture_output=True, text=True,
                                      timeout=3600)
                cell["subprocess_wall_s"] = round(
                    time.perf_counter() - t0, 1)
                try:
                    cell.update(
                        json.loads(proc.stdout.strip().splitlines()[-1]))
                except (json.JSONDecodeError, IndexError):
                    cell["error"] = (proc.stderr or "no output")[-400:]
            except subprocess.TimeoutExpired as e:
                # one hung condition (dead TPU tunnel) must not discard
                # the conditions already measured
                cell["subprocess_wall_s"] = round(
                    time.perf_counter() - t0, 1)
                cell["error"] = (f"timeout after 3600s: "
                                 f"{(e.stderr or '')[-300:]}")
            conditions.append(cell)
            print(f"[compile_econ] {phase} bucket={bucket}: "
                  f"bill {cell.get('train_compile_bill_s')}s "
                  f"wall {cell.get('schedule_wall_s')}s",
                  file=sys.stderr, flush=True)
    import jax

    result = {
        "platform": jax.devices()[0].platform,
        "device": str(jax.devices()[0].device_kind),
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "conditions": conditions,
    }
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    return result


def markdown_table(result: dict) -> str:
    lines = [
        "| bucket | cache | compile bill (s) | schedule wall (s) |",
        "|---|---|---|---|",
    ]
    for c in result["conditions"]:
        lines.append(
            f"| {c['bucket']} | {c['cache']} "
            f"| {c.get('train_compile_bill_s', c.get('error', '—'))} "
            f"| {c.get('schedule_wall_s', '—')} |"
        )
    return "\n".join(lines)


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--out", default="logs/compile_economics.json")
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--run-one", action="store_true",
                    help="internal: run one condition in this process")
    ap.add_argument("--bucket", type=int, default=1)
    ap.add_argument("--cache-dir", default="")
    args = ap.parse_args(argv)
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    if args.run_one:
        if args.cache_dir:
            from torchpruner_tpu.utils.compilation_cache import (
                enable_persistent_cache,
            )

            enable_persistent_cache(args.cache_dir)
        print(json.dumps(run_schedule(args.bucket, args.steps, args.smoke)),
              flush=True)
        return
    result = orchestrate(args.steps, args.smoke, args.out)
    print(markdown_table(result))
    print(f"wrote {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()

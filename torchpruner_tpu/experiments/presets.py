"""Named experiment presets — the five configs of BASELINE.json, plus
a digits32 variant of the VGG16 recipe runnable end to end in
environments without the CIFAR-10 files.

Each preset returns an :class:`~torchpruner_tpu.utils.config.ExperimentConfig`
ready for :func:`~torchpruner_tpu.experiments.prune_retrain.run_prune_retrain`
(or the robustness sweep for the VGG16 recipe).  ``smoke=True`` swaps in the
miniature model/dataset variants with the identical block structure, so every
preset's full code path runs on one CPU in seconds — the scaled configs are
the same recipe at size.
"""

from __future__ import annotations

from typing import Callable, Dict

from torchpruner_tpu.utils.config import ExperimentConfig


def mnist_mlp_shapley(smoke: bool = False) -> ExperimentConfig:
    """Config 0: the reference's "Pruning Untrained Networks" MNIST MLP —
    784-2024-2024-10 FC net, Shapley attribution on both hidden layers,
    all-negative-attribution prune, short fine-tune.  The smoke variant
    runs the identical recipe on the 64-64-64-10 digits MLP in seconds on
    one CPU — the obs quick-lane smoke target (tests/test_obs.py)."""
    return ExperimentConfig(
        name="mnist_mlp_shapley",
        model="digits_fc_tiny" if smoke else "mnist_fc",
        dataset="digits_flat" if smoke else "mnist_flat",
        method="shapley",
        method_kwargs={"sv_samples": 2 if smoke else 5},
        policy="negative",
        finetune_epochs=1,
        score_examples=32 if smoke else 1000,
        batch_size=32 if smoke else 64,
        eval_batch_size=64 if smoke else 250,
        lr=0.05 if smoke else 0.01,
    )


def vgg16_layerwise(smoke: bool = False) -> ExperimentConfig:
    """Config 1 — the reference's own recipe: CIFAR-10 VGG16 layerwise
    pruning (VGG notebook; SURVEY.md §2.8)."""
    return ExperimentConfig(
        name="vgg16_layerwise",
        model="vgg16_bn_tiny" if smoke else "vgg16_bn",
        dataset="cifar10",
        experiment="robustness",
        method="shapley" if smoke else "all",
        method_kwargs={"sv_samples": 5},
        score_examples=64 if smoke else 1000,
        eval_batch_size=64 if smoke else 250,
        score_dtype="float32" if smoke else "bfloat16",  # MXU-rate sweep
        results_path="" if smoke else "logs/vgg16_sweep_results.json",
    )


def vgg16_digits32_layerwise(smoke: bool = False) -> ExperimentConfig:
    """Config 1b — the same two-phase recipe (pretrain → full layerwise
    sweep) runnable END TO END in this environment: digits32 is REAL
    image data (sklearn digit scans at CIFAR-10 geometry), so the sweep
    scores a genuinely trained full-width VGG16-bn without the CIFAR-10
    distribution files.  One command, no checkpoint hand-off."""
    return ExperimentConfig(
        name="vgg16_digits32_layerwise",
        model="vgg16_bn_tiny" if smoke else "vgg16_bn",
        dataset="digits32",
        experiment="train_robustness",
        epochs=1 if smoke else 12,
        batch_size=64 if smoke else 128,
        optimizer="adam",
        lr=1e-3,
        lr_schedule="constant",
        compute_dtype="float32" if smoke else "bfloat16",
        method="shapley" if smoke else "all",
        method_kwargs={"sv_samples": 5},
        score_examples=64 if smoke else 300,
        eval_batch_size=64 if smoke else 300,
        score_dtype="float32" if smoke else "bfloat16",
        results_path="" if smoke else "logs/vgg16_digits32_sweep.json",
    )


def resnet50_taylor(smoke: bool = False) -> ExperimentConfig:
    """Config 2: ResNet-50 / ImageNet structured filter pruning, Taylor
    criterion."""
    return ExperimentConfig(
        name="resnet50_taylor",
        model="resnet20_cifar" if smoke else "resnet50",
        dataset="cifar10" if smoke else "imagenet",
        n_classes=10 if smoke else 1000,
        method="taylor",
        policy="fraction",
        fraction=0.25,
        finetune_epochs=0 if smoke else 1,
        score_examples=64 if smoke else 1000,
        eval_batch_size=64 if smoke else 250,
        lr=0.01,
        momentum=0.9,
    )


def bert_glue_sensitivity(smoke: bool = False) -> ExperimentConfig:
    """Config 3: BERT-base Linear-layer pruning on GLUE, Sensitivity
    criterion — targets the per-block FFN hidden Linears."""
    return ExperimentConfig(
        name="bert_glue_sensitivity",
        model="bert_tiny" if smoke else "bert_base",
        dataset="glue_tiny" if smoke else "glue_sst2",
        n_classes=2,
        method="sensitivity",
        policy="fraction",
        fraction=0.3,
        target_filter=("_mlp/",),
        score_examples=64 if smoke else 1000,
        batch_size=16 if smoke else 32,
        eval_batch_size=64 if smoke else 128,
        lr=3e-3,
        compute_dtype="float32" if smoke else "bfloat16",
    )


def vit_head_mlp_shapley(smoke: bool = False) -> ExperimentConfig:
    """Config 4: ViT-B/16 attention-head + MLP pruning, Shapley
    (sv_samples=5)."""
    return ExperimentConfig(
        name="vit_head_mlp_shapley",
        model="vit_tiny" if smoke else "vit_b16",
        dataset="tiny_images16" if smoke else "imagenet",
        n_classes=10 if smoke else 1000,
        method="shapley",
        method_kwargs={"sv_samples": 5},
        policy="negative",
        target_filter=("_attn/", "_mlp/"),
        score_examples=64 if smoke else 1000,
        eval_batch_size=64 if smoke else 128,
    )


def llama3_ffn_taylor(smoke: bool = False) -> ExperimentConfig:
    """Config 5: Llama-3-8B FFN channel pruning + fine-tune (pjit FSDP).
    Attribution on LM loss; FFN GatedDense channels only; the full-size run
    shards over a ``{"data": 8, "model": 8}`` mesh (v5p-64-shaped)."""
    return ExperimentConfig(
        name="llama3_ffn_taylor",
        model="llama_tiny" if smoke else "llama3_8b",
        dataset="lm_tiny" if smoke else "lm_corpus",
        loss="lm_cross_entropy",
        method="taylor",
        policy="fraction",
        fraction=0.25,
        target_filter=("_ffn/",),
        finetune_epochs=0 if smoke else 1,
        score_examples=32 if smoke else 512,
        batch_size=8 if smoke else 16,
        eval_batch_size=16 if smoke else 32,
        lr=1e-4,
        mesh={} if smoke else {"data": 8, "model": 8},
        # TPU-native at 8B scale: bf16 fwd/bwd (f32 masters) and
        # recompute-in-backward blocks so S=2048 activations fit HBM
        compute_dtype="float32" if smoke else "bfloat16",
        remat=not smoke,
    )


PRESETS: Dict[str, Callable[..., ExperimentConfig]] = {
    "mnist_mlp_shapley": mnist_mlp_shapley,
    "vgg16_layerwise": vgg16_layerwise,
    "vgg16_digits32_layerwise": vgg16_digits32_layerwise,
    "resnet50_taylor": resnet50_taylor,
    "bert_glue_sensitivity": bert_glue_sensitivity,
    "vit_head_mlp_shapley": vit_head_mlp_shapley,
    "llama3_ffn_taylor": llama3_ffn_taylor,
}


def preset_names() -> tuple:
    """Every shipped preset name — the sweep surface CI lints
    (``--lint <name>`` must report zero errors for each) and the CLI
    lists."""
    return tuple(PRESETS)


def get_preset(name: str, smoke: bool = False) -> ExperimentConfig:
    if name not in PRESETS:
        raise KeyError(f"unknown preset {name!r}; known: {list(PRESETS)}")
    return PRESETS[name](smoke=smoke)

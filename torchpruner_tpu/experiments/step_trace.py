"""Train-step anatomy: profile one model's train step and print where
the milliseconds go.

The round-2 verdict's MFU question ("where do the VGG16 step's 75.6 ms
go?") needs a per-op breakdown, not another stopwatch number.  This
experiment builds a Trainer for a model-zoo entry, runs the compiled
step under ``jax.profiler``, and prints the
:mod:`~torchpruner_tpu.utils.trace_analysis` summary — conv vs matmul vs
fusion vs copy vs infeed — plus the usual steady-state timing for
cross-checking.

Run: ``python -m torchpruner_tpu.experiments.step_trace --model
vgg16_bn --batch 256 [--dtype bf16] [--steps 5] [--trace-dir
logs/step_trace]``.
"""

from __future__ import annotations

import json
import sys


def run(model_name: str, batch: int, dtype: str, steps: int,
        trace_dir: str, smoke: bool = False) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from torchpruner_tpu.experiments.prune_retrain import MODEL_REGISTRY
    from torchpruner_tpu.train.loop import Trainer
    from torchpruner_tpu.utils import profiling
    from torchpruner_tpu.utils.losses import (
        cross_entropy_loss,
        lm_cross_entropy_loss,
    )
    from torchpruner_tpu.utils.trace_analysis import (
        markdown_summary,
        summarize_trace,
    )

    model_fn, _ = MODEL_REGISTRY[model_name]
    model = model_fn()
    # (S, vocab) output = causal LM (next-token loss, targets = inputs);
    # (n_classes,) output = classification
    is_lm = len(model.out_shape()) == 2
    loss_fn = lm_cross_entropy_loss if is_lm else cross_entropy_loss
    if smoke:
        batch = min(batch, 8)
    compute_dtype = {"bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
                     "f32": None, "float32": None}[dtype]
    trainer = Trainer.create(model, optax.adam(1e-3), loss_fn, seed=0,
                             compute_dtype=compute_dtype)
    x = jnp.asarray(np.asarray(model.example_input(batch)))
    if is_lm:
        y = x  # next-token loss on the inputs
    else:
        y = jnp.asarray(
            np.random.default_rng(0).integers(
                0, model.out_shape()[-1], size=(batch,)
            ).astype("int32"))

    stats = profiling.time_train_step(trainer, x, y, iters=max(3, steps),
                                      warmup=3, chained=True)
    with profiling.trace(trace_dir):
        for _ in range(steps):
            trainer.step(x, y)
        profiling.hard_fence(trainer.params)
    summary = summarize_trace(trace_dir)
    summary["steps_traced"] = steps
    chained = profiling.steady_s(stats)
    summary["p50_step_ms"] = round(stats["p50_s"] * 1e3, 3)
    summary["chained_step_ms"] = round(chained * 1e3, 3)
    # device-level step time straight from the profiler's XLA-Ops track:
    # on the tunnelled single-chip setup the wall-clock stopwatches carry
    # per-step host/tunnel overhead the hardware never sees — this is the
    # number that says what the CHIP does (and the MFU the same step
    # would reach fed locally at scale)
    summary["device_step_ms"] = round(summary["total_ms"] / steps, 3)
    summary["model"] = model_name
    summary["batch"] = batch
    summary["dtype"] = dtype
    summary["platform"] = jax.devices()[0].platform
    if compute_dtype is jnp.bfloat16:
        from torchpruner_tpu.utils.flops import (
            flag_implausible_mfu,
            model_cost,
            peak_bf16_flops,
        )

        peak = peak_bf16_flops(jax.devices()[0])
        _, fwd_flops = model_cost(model, trainer.params, trainer.state,
                                  batch_size=batch)
        if peak and fwd_flops:
            # an empty/deviceless trace yields device_step_ms ~ 0 — a
            # division there must degrade to "no reading", not crash
            # after the expensive profile run
            dev_s = summary["device_step_ms"] / 1e3
            if dev_s > 1e-6:
                summary["mfu_device"] = round(
                    (3.0 * fwd_flops / dev_s) / peak, 4)
            if chained > 0:
                summary["mfu_chained"] = round(
                    (3.0 * fwd_flops / chained) / peak, 4)
            flag_implausible_mfu(summary, "mfu_device", "mfu_chained")
    print(f"model {model_name} batch {batch} {dtype}: device step "
          f"{summary['device_step_ms']} ms, chained "
          f"{summary['chained_step_ms']} ms, fenced p50 "
          f"{summary['p50_step_ms']} ms over {steps} traced steps\n",
          flush=True)
    print(markdown_summary(summary, top=20))
    return summary


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", default="vgg16_bn")
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--dtype", default="bf16",
                    choices=["bf16", "bfloat16", "f32", "float32"])
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--trace-dir", default="logs/step_trace")
    ap.add_argument("--out", default="",
                    help="also write the JSON summary here")
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args(argv)
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    summary = run(args.model, args.batch, args.dtype, args.steps,
                  args.trace_dir, smoke=args.smoke)
    if args.out:
        import os

        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=1)
        print(f"wrote {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()

"""Flash-attention payoff sweep: flash (Pallas) vs XLA einsum attention
across sequence lengths.

The round-2 measurement showed the Pallas kernel at speed *parity* with
XLA at S=2048 with a 15.8× temp-memory win — the payoff claim (longer
sequences than the O(S²) einsum path can run, and wins at the long end)
was never demonstrated.  This sweep produces the crossover table:

  for S in {2k, 8k, 16k, 32k}:  fwd+bwd grad-step time and compiled
  temp memory for both paths (B·H scaled down as S grows so the XLA
  path's O(S²) logits still have a chance to fit), plus block_q/block_k
  tuning for the flash kernel at the long end.

Run on TPU:  ``python -m torchpruner_tpu.experiments.flash_sweep
[--out logs/flash_sweep.json] [--tune]``.  Emits one JSON with every
cell (errors recorded per cell — an XLA OOM at long S IS the result),
plus markdown table rows for PERF.md.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Optional

#: (S, B, H) — keep B*S*H*Dh roughly constant so q/k/v stay small while
#: the XLA path's (B, H, S, S) f32 logits grow 4x per row: 2k -> 1 GB,
#: 8k -> 4 GB, 16k -> 8 GB, 32k -> 16 GB (past a v5e's HBM *with* the
#: rest of the step; where it dies, that's the crossover).
SWEEP = [
    (2048, 4, 8),
    (8192, 2, 4),
    (16384, 1, 4),
    (32768, 1, 2),
]
DH = 64


def _measure(fn, q, k, v, *, iters: int = 5, warmup: int = 2,
             block_q: Optional[int] = None,
             block_k: Optional[int] = None) -> dict:
    import jax
    import jax.numpy as jnp

    kw = {}
    if block_q or block_k:
        kw = {"block_q": block_q, "block_k": block_k}

    def loss(q_, k_, v_):
        return jnp.sum(fn(q_, k_, v_, causal=True, **kw)
                       .astype(jnp.float32))

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    out = {}
    try:
        t0 = time.perf_counter()
        compiled = g.lower(q, k, v).compile()
        out["compile_s"] = round(time.perf_counter() - t0, 2)
        mem = compiled.memory_analysis()
        out["temp_mb"] = round(mem.temp_size_in_bytes / 2**20, 1)
        out["argument_mb"] = round(mem.argument_size_in_bytes / 2**20, 1)
    except Exception as e:  # noqa: BLE001 - OOM/lowering failure IS data
        out["error"] = f"{type(e).__name__}: {e}"[:300]
        return out
    try:
        # time the AOT executable directly — going back through g would
        # re-trace and pay the (dominant at long S) compile a second time
        from torchpruner_tpu.utils.profiling import steady_s, time_fn

        stats = time_fn(compiled, q, k, v, iters=iters, warmup=warmup,
                        chained=True)
        out["ms"] = round(steady_s(stats) * 1e3, 3)
        out["ms_fenced_p50"] = round(stats["p50_s"] * 1e3, 3)
        # one post-timing capture window: top-5 kernel rows per sweep
        # point — op-level evidence for the block-size retune (ROADMAP
        # item 2); degrades to no row on failure
        from torchpruner_tpu.obs.profile import OneShotCapture

        with OneShotCapture(out, steps=1):
            jax.block_until_ready(compiled(q, k, v))
    except Exception as e:  # noqa: BLE001 - runtime OOM IS data
        out["error"] = f"{type(e).__name__}: {e}"[:300]
    return out


def run_sweep(tune: bool = False, smoke: bool = False) -> dict:
    import jax
    import jax.numpy as jnp

    from torchpruner_tpu.ops.flash_attention import (
        _xla_attention,
        flash_attention,
    )

    sweep = [(256, 2, 2), (512, 1, 2)] if smoke else SWEEP
    rows = []
    for S, B, H in sweep:
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q, k, v = (jax.random.normal(kk, (B, S, H, DH), jnp.bfloat16)
                   for kk in ks)
        row = {"S": S, "B": B, "H": H, "Dh": DH}
        print(f"[flash_sweep] S={S} B={B} H={H} ...", file=sys.stderr,
              flush=True)
        row["flash"] = _measure(flash_attention, q, k, v)
        from torchpruner_tpu.ops import flash_attention as F

        if (F.FLASH_BWD_XLA_MIN_S is not None
                and S >= F.FLASH_BWD_XLA_MIN_S):
            # the default route at this length recomputes the backward
            # through XLA (the kernel bwd's remote compile 500s on the
            # tunnel) — record that, then ATTEMPT the pure kernel
            # backward anyway so a healthier environment measures it
            row["flash"]["note"] = ("bwd via XLA fallback "
                                    "(FLASH_BWD_XLA_MIN_S)")
            old = F.FLASH_BWD_XLA_MIN_S
            F.FLASH_BWD_XLA_MIN_S = None
            try:
                row["flash_kernel_bwd"] = _measure(
                    flash_attention, q, k, v)
            finally:
                F.FLASH_BWD_XLA_MIN_S = old
        row["xla"] = _measure(_xla_attention, q, k, v)
        if row["flash"].get("ms") and row["xla"].get("ms"):
            row["speedup"] = round(row["xla"]["ms"] / row["flash"]["ms"], 3)
        if row["flash"].get("temp_mb") and row["xla"].get("temp_mb"):
            row["mem_ratio"] = round(
                row["xla"]["temp_mb"] / row["flash"]["temp_mb"], 1)
        rows.append(row)

    tuning = None
    if tune:
        # block tuning at the longest S that ran: bigger KV blocks
        # amortize loop overhead; VMEM caps the product
        best = None
        tuning = []
        long_rows = [r for r in rows if r["flash"].get("ms")]
        if long_rows:
            S, B, H = (lambda r: (r["S"], r["B"], r["H"]))(long_rows[-1])
            ks = jax.random.split(jax.random.PRNGKey(0), 3)
            q, k, v = (jax.random.normal(kk, (B, S, H, DH), jnp.bfloat16)
                       for kk in ks)
            for bq, bk in ((128, 128), (128, 256), (256, 128), (256, 256),
                           (128, 512), (512, 128), (256, 512), (512, 512)):
                cell = {"S": S, "block_q": bq, "block_k": bk}
                cell.update(_measure(flash_attention, q, k, v,
                                     block_q=bq, block_k=bk))
                tuning.append(cell)
                print(f"[flash_sweep] tune bq={bq} bk={bk}: "
                      f"{cell.get('ms', cell.get('error'))}",
                      file=sys.stderr, flush=True)
                if cell.get("ms") and (best is None or
                                       cell["ms"] < best["ms"]):
                    best = cell
        if best:
            tuning.append({"best": best})
            # persist the winner where the dispatch path reads it
            # (ops/autotune.py): the sweep's tuning becomes every later
            # run's default for this (head-dim, seq-bucket, dtype)
            from torchpruner_tpu.ops import autotune

            key = autotune.record(
                autotune.KIND_FLASH, DH, best["S"], jnp.bfloat16,
                (best["block_q"], best["block_k"]), ms=best.get("ms"))
            tuning.append({"recorded": key,
                           "cache": autotune.cache_path()})

    out = {
        "device": str(jax.devices()[0].device_kind),
        "platform": jax.devices()[0].platform,
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "rows": rows,
    }
    if tuning is not None:
        out["tuning"] = tuning
    return out


def markdown_table(result: dict) -> str:
    lines = [
        "| S | B×H | flash ms | xla ms | speedup | flash temp MB "
        "| xla temp MB | mem ratio |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for r in result["rows"]:
        f, x = r["flash"], r["xla"]
        lines.append(
            f"| {r['S']} | {r['B']}×{r['H']} "
            f"| {f.get('ms', f.get('error', '—'))} "
            f"| {x.get('ms', x.get('error', '—'))} "
            f"| {r.get('speedup', '—')} "
            f"| {f.get('temp_mb', '—')} | {x.get('temp_mb', '—')} "
            f"| {r.get('mem_ratio', '—')} |"
        )
    return "\n".join(lines)


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="logs/flash_sweep.json")
    ap.add_argument("--tune", action="store_true",
                    help="also tune block_q/block_k at the longest "
                    "runnable S")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes (CPU path validation)")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args(argv)
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    result = run_sweep(tune=args.tune, smoke=args.smoke)
    import os

    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)
    print(markdown_table(result))
    print(f"wrote {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()

"""Layerwise-robustness ablation sweep — the reference's headline experiment
("CIFAR-10 - VGG16 - Layerwise robustness.ipynb", SURVEY.md §3.5): for each
prunable layer × attribution method, zero units one at a time in
ascending-score order and log test loss/acc per removal count.

The reference runs ``n_units`` separate suffix forwards per layer per method
in Python — 6.5 h wall-clock on a CUDA GPU (BASELINE.md).  Here the whole
cumulative-ablation walk over a layer is ONE ``lax.scan`` inside one jit
per batch: the scan carries the cumulative unit mask, and each step's suffix
evaluation is a batched MXU matmul.
"""

from __future__ import annotations

import functools
import logging
import time
from typing import Callable, Dict, List, Optional, Sequence

import jax
import numpy as np
import jax.numpy as jnp

from torchpruner_tpu import obs
from torchpruner_tpu.core.graph import find_best_evaluation_layer, pruning_graph
from torchpruner_tpu.core.segment import SegmentedModel


def _walk_from_z(model, eval_layer, loss_fn, compute_dtype, params, state,
                 z, y, rankings):
    """The cumulative-ablation walk given the eval-site activation ``z``
    — the shared core of the uncached and capture-cached ablation
    programs (one body, so the two paths are the same computation by
    construction)."""
    from torchpruner_tpu.utils.losses import prediction_counts

    n = z.shape[-1]

    def run_suffix(zz):
        logits, _ = model.apply(params, zz, state=state,
                                train=False, from_layer=eval_layer)
        if compute_dtype is not None:
            logits = logits.astype(jnp.float32)
        return logits

    def walk(ranking):
        def step(mask, u):
            mask = mask.at[u].set(0.0)
            logits = run_suffix(z * mask)
            losses = loss_fn(logits, y)
            correct, _ = prediction_counts(logits, y)
            return mask, (jnp.sum(losses), correct)

        _, (loss_sums, corrects) = jax.lax.scan(
            step, jnp.ones((n,), z.dtype), ranking
        )
        return loss_sums, corrects

    loss_sums, corrects = jax.vmap(walk)(rankings)  # (R, n) each
    base_logits = run_suffix(z)
    base_correct, n_pred = prediction_counts(base_logits, y)
    base_loss = jnp.sum(loss_fn(base_logits, y))
    return loss_sums, corrects, base_loss, base_correct, n_pred


@functools.lru_cache(maxsize=512)
def _ablation_fn_batch(model: SegmentedModel, eval_layer: str, loss_fn,
                       compute_dtype=None):
    """jit: (params, state, x, y, rankings (R, n)) -> per-ranking
    (loss_sums, corrects) (R, n) + base metrics — the sweep runs one
    layer's whole method panel (8 methods x stochastic repeats = 14
    walks) as a single scan whose suffix forwards batch over the R
    rankings, so small-batch suffix matmuls tile the MXU R x better and
    the walk launches once per (layer, batch).

    ``compute_dtype=bfloat16`` runs the forwards at MXU rate
    (params/activations cast; logits promoted to f32 before the loss, so
    loss sums accumulate in f32 — the shared mixed-precision policy)."""

    from torchpruner_tpu.utils.dtypes import cast_floats

    @jax.jit
    def fn(params, state, x, y, rankings):
        if compute_dtype is not None:
            params = cast_floats(params, compute_dtype)
            x = cast_floats(x, compute_dtype)
        z, _ = model.apply(params, x, state=state, train=False,
                           to_layer=eval_layer)
        return _walk_from_z(model, eval_layer, loss_fn, compute_dtype,
                            params, state, z, y, rankings)

    return fn


@functools.lru_cache(maxsize=512)
def _ablation_fn_batch_from_z(model: SegmentedModel, eval_layer: str,
                              loss_fn, compute_dtype=None):
    """jit: (params, state, z, y, rankings) — :func:`_ablation_fn_batch`
    resuming from the CAPTURED eval-site activation (the one-pass sweep
    engine's phase-2 program; ``z`` was already computed under the same
    cast policy at capture-fill time)."""

    from torchpruner_tpu.utils.dtypes import cast_floats

    @jax.jit
    def fn(params, state, z, y, rankings):
        if compute_dtype is not None:
            params = cast_floats(params, compute_dtype)
        return _walk_from_z(model, eval_layer, loss_fn, compute_dtype,
                            params, state, z, y, rankings)

    return fn


def ablation_curves_batch(
    model: SegmentedModel,
    params,
    state,
    layer: str,
    rankings,
    data,
    loss_fn,
    *,
    eval_layer: Optional[str] = None,
    mesh=None,
    data_axis: str = "data",
    compute_dtype=None,
    capture_cache=None,
) -> List[Dict[str, np.ndarray]]:
    """Batched :func:`ablation_curve`: ``rankings`` is ``(R, n)``; returns
    R curve dicts in order.  One vmapped scan per data batch evaluates
    every ranking simultaneously; with ``mesh`` the batch dim shards over
    ``data_axis`` (params/rankings replicated) and the same program runs
    SPMD.

    ``capture_cache`` (an ``attributions.base.ActivationCache`` built from
    the same model/data/dtype — the sweep's one-pass engine) supplies the
    eval-site activation per batch, so the walk resumes from ``z`` instead
    of recomputing the prefix; cached activations carry their fill-time
    placement, so the ``mesh`` batch sharding is already applied."""
    eval_layer = eval_layer or layer
    rankings = jnp.asarray(np.asarray(rankings, dtype=np.int32))

    use_cache = (
        capture_cache is not None
        and capture_cache.has(eval_layer)
        and capture_cache.provides_for(model, params, state, data,
                                       compute_dtype)
    )
    if capture_cache is not None and not use_cache:
        capture_cache.record_miss(eval_layer)
    fn = (_ablation_fn_batch_from_z(model, eval_layer, loss_fn,
                                    compute_dtype)
          if use_cache else
          _ablation_fn_batch(model, eval_layer, loss_fn, compute_dtype))

    def put(t):  # identity on a single device
        return t

    if mesh is not None:
        from torchpruner_tpu.parallel.sharding import (
            batch_sharding,
            replicate,
        )

        repl = replicate(mesh)
        params = jax.device_put(params, repl)
        if state is not None:
            state = jax.device_put(state, repl)
        rankings = jax.device_put(rankings, repl)
        n_shard = mesh.shape[data_axis]
        bs = batch_sharding(mesh, data_axis)

        def put(t):
            if t.shape[0] % n_shard:
                raise ValueError(
                    f"batch size {t.shape[0]} not divisible by mesh axis "
                    f"{data_axis}={n_shard}; use drop_remainder batches"
                )
            return jax.device_put(t, bs)

    tot_l = tot_c = None
    base_l = base_c = 0.0
    n_examples = 0
    n_preds = 0
    if use_cache:
        capture_cache.record_hit(eval_layer)
        batches = capture_cache.batches_for(eval_layer)
    else:
        batches = ((put(x), put(y))
                   for x, y in (data() if callable(data) else data))
    for z_or_x, y in batches:
        l, c, bl, bc, n_pred = fn(params, state, z_or_x, y, rankings)
        tot_l = l if tot_l is None else tot_l + l
        tot_c = c if tot_c is None else tot_c + c
        base_l += float(bl)
        base_c += float(bc)
        n_examples += z_or_x.shape[0]
        n_preds += int(n_pred)
    return [
        {
            "loss": np.asarray(tot_l[r]) / n_examples,
            "acc": np.asarray(tot_c[r]) / n_preds,
            "base_loss": base_l / n_examples,
            "base_acc": base_c / n_preds,
        }
        for r in range(rankings.shape[0])
    ]


def ablation_curve(
    model: SegmentedModel,
    params,
    state,
    layer: str,
    ranking: np.ndarray,
    data,
    loss_fn,
    *,
    eval_layer: Optional[str] = None,
    mesh=None,
    data_axis: str = "data",
    compute_dtype=None,
) -> Dict[str, np.ndarray]:
    """Simulated pruning of ``layer``'s units in ``ranking`` order.

    Returns ``{"loss": (n,), "acc": (n,), "base_loss": float,
    "base_acc": float}`` — test loss/accuracy after each cumulative removal
    (the reference's cell-8 inner loop, one scan per batch here).  The
    R = 1 case of :func:`ablation_curves_batch` (one implementation for
    both paths); ``mesh`` shards the example dim over ``data_axis`` for
    the SPMD sweep.
    """
    return ablation_curves_batch(
        model, params, state, layer,
        np.asarray(ranking, dtype=np.int32)[None], data, loss_fn,
        eval_layer=eval_layer, mesh=mesh, data_axis=data_axis,
        compute_dtype=compute_dtype,
    )[0]


def loss_increase_auc(curve: Dict[str, np.ndarray]) -> float:
    """Average test-loss increase per unit removed — the reference's summary
    statistic (VGG notebook cell 11; lower = better ranking)."""
    return float(np.mean(curve["loss"] - curve["base_loss"]))


PANEL_VERSION = "8m-sv5-runs3-adam1e3-bf16-v1"


def method_panel(model, params, batches, loss_fn, *, state=None,
                 compute_dtype=None, sv_samples: int = 5):
    """The reference's 8-method scoring panel (VGG notebook cell 8 —
    random / weight_norm / apoz / sensitivity / taylor / taylor_signed /
    sv / sv_mean+2std) as metric factories for
    :func:`layerwise_robustness`.  ONE definition shared by the bench
    sweep leg and :mod:`~.sweep_scaling`, so the scaling measurement
    always calibrates the exact panel the headline runs; bump
    ``PANEL_VERSION`` whenever the dict, ``sv_samples``, or the
    stochastic-run policy changes (it keys the sweep's resume scratch).
    """
    from torchpruner_tpu.experiments.prune_retrain import build_metric

    def factory(method, reduction="mean", **kw):
        def make(run=0):
            return build_metric(
                method, model, params, batches, loss_fn,
                state=state, reduction=reduction, seed=run,
                compute_dtype=compute_dtype, **kw)
        return make

    return {
        "random": factory("random"),
        "weight_norm": factory("weight_norm"),
        "apoz": factory("apoz"),
        "sensitivity": factory("sensitivity"),
        "taylor": factory("taylor"),
        "taylor_signed": factory("taylor", signed=True),
        "sv": factory("shapley", sv_samples=sv_samples),
        "sv_mean+2std": factory("shapley", reduction="mean+2std",
                                sv_samples=sv_samples),
    }


def layerwise_robustness(
    model: SegmentedModel,
    params,
    state,
    test_data,
    methods: Dict[str, Callable[[], "AttributionMetric"]],
    loss_fn,
    *,
    layers: Optional[Sequence[str]] = None,
    runs_stochastic: int = 3,
    stochastic: Sequence[str] = ("random", "shapley", "sv"),
    find_best_evaluation_layer_: bool = True,
    mesh=None,
    data_axis: str = "data",
    compute_dtype=None,
    capture: bool = True,
    verbose: bool = True,
    on_layer: Optional[Callable[[str, Dict[str, List[Dict]]], None]] = None,
) -> Dict[str, Dict[str, List[Dict]]]:
    """The full sweep: every prunable layer × every method (×
    ``runs_stochastic`` repeats for stochastic methods).

    ``methods`` maps display names to metric factories taking an optional
    run index (``factory(run)``), so stochastic repeats draw DIFFERENT
    randomness — seed the metric with ``base_seed + run`` (zero-arg
    factories are accepted but make the repeats identical).  Returns
    ``results[layer][method] = [ {scores, loss, acc, auc, seconds}, ... ]``.

    ``capture=True`` (default) runs the one-pass capture engine: ONE
    compiled program per params version computes every layer's eval-site
    activation per batch (``attributions.base.ActivationCache``), and all
    methods, stochastic runs, and the phase-2 ablation walks on a layer
    consume that shared activation instead of each re-running the prefix
    forward — O(L²) prefix layer-forwards drop to O(L) and the L prefix
    executables collapse into one.  Metrics built from different
    params/data than the sweep's fall back to the uncached path (counted
    as ``attrib_capture_misses``); results are identical either way
    (tests/test_capture.py pins equality on/off).

    ``on_layer(layer, results[layer])`` fires after each layer's panel
    completes — callers use it to checkpoint the multi-hour sweep so a
    kill mid-run keeps the finished layers (bench.py's streamed
    snapshots).  Callback errors are the caller's problem; keep it cheap.
    """
    import inspect

    if layers is None:
        layers = [g.target for g in pruning_graph(model)]
    cache = None
    layer_sites: List[str] = []
    if capture and layers:
        from torchpruner_tpu.attributions.base import ActivationCache

        layer_sites = [
            find_best_evaluation_layer(model, layer)
            if find_best_evaluation_layer_ else layer
            for layer in layers
        ]
        cache = ActivationCache(
            model, params, test_data, sites=layer_sites, state=state,
            compute_dtype=compute_dtype, mesh=mesh, data_axis=data_axis,
        )
    if mesh is not None:
        # replicate ONCE for the whole sweep; ablation_curve's own
        # device_put then short-circuits on the already-placed trees
        # (without this, every layer x method x run curve would re-
        # broadcast the full model)
        from torchpruner_tpu.parallel.sharding import replicate

        repl = replicate(mesh)
        params = jax.device_put(params, repl)
        if state is not None:
            state = jax.device_put(state, repl)
        if cache is not None:
            # the replicated copies hold the same values — keep the
            # cache's identity guards valid for the phase-2 walks, and
            # let the fill reuse the placed trees instead of
            # re-replicating from host
            cache.alias_params(params)
            if state is not None:
                cache.alias_state(state)
    results: Dict[str, Dict[str, List[Dict]]] = {}
    for li, layer in enumerate(layers):
        with obs.span("robustness_layer", layer=layer):
            results[layer] = {}
            # The ablation mask point is always the post-BN/activation
            # layer, for every method — matching the reference sweep,
            # which masks at find_best_module_for_attributions(module)
            # regardless of how scores were computed (VGG notebook cell
            # 8).  Zeroing there is what unit removal actually does.
            eval_layer = (
                find_best_evaluation_layer(model, layer)
                if find_best_evaluation_layer_
                else layer
            )
            # phase 1: score every (method, run); collect the rankings
            pending = []  # (name, scores, score_seconds)
            for name, factory in methods.items():
                n_runs = (
                    runs_stochastic
                    if any(s in name.lower() for s in stochastic)
                    else 1
                )
                takes_run = bool(inspect.signature(factory).parameters)
                fbel = find_best_evaluation_layer_
                for run_idx in range(n_runs):
                    t0 = time.perf_counter()
                    metric = factory(run_idx) if takes_run else factory()
                    if cache is not None:
                        # every method × run on this layer consumes the
                        # ONE captured activation (mismatched metrics
                        # fall back and count as misses)
                        metric.capture_cache = cache
                    scores = metric.run(
                        layer, find_best_evaluation_layer=fbel,
                    )
                    pending.append(
                        (name, scores, time.perf_counter() - t0))

            # phase 2: ONE batched walk for the whole method panel (each
            # data batch's suffix forwards vectorize over all rankings;
            # under a mesh the example dim additionally shards over the
            # data axis)
            if not pending:
                continue
            t0 = time.perf_counter()
            curves = ablation_curves_batch(
                model, params, state, layer,
                np.stack([np.argsort(s) for _, s, _ in pending]),
                test_data, loss_fn,
                eval_layer=eval_layer, mesh=mesh, data_axis=data_axis,
                compute_dtype=compute_dtype, capture_cache=cache,
            )
            walk_share = (time.perf_counter() - t0) / len(pending)

            for (name, scores, score_s), curve in zip(pending, curves):
                results[layer].setdefault(name, []).append({
                    "scores": scores,
                    "loss": curve["loss"],
                    "acc": curve["acc"],
                    "base_loss": curve["base_loss"],
                    "base_acc": curve["base_acc"],
                    "auc": loss_increase_auc(curve),
                    "seconds": score_s + walk_share,
                })
            # provenance: one ledger record per finished layer panel —
            # the sweep's unit of round-level evidence (method AUCs; raw
            # curves stay in results_path/journal artifacts)
            obs.record_sweep_layer(layer=layer, eval_layer=eval_layer,
                                   methods={
                name: {
                    "auc_mean": float(np.mean([r["auc"] for r in runs])),
                    "auc_std": float(np.std([r["auc"] for r in runs])),
                    "n_runs": len(runs),
                    "seconds_mean": float(np.mean(
                        [r["seconds"] for r in runs])),
                }
                for name, runs in results[layer].items()
            })
            if verbose:
                for name, runs in results[layer].items():
                    aucs = [r["auc"] for r in runs]
                    print(
                        f"[robustness] {layer} / {name}: auc "
                        f"{np.mean(aucs):.4f} ± {np.std(aucs):.4f} "
                        f"({runs[0]['seconds']:.1f}s/run)",
                        flush=True,
                    )
            if on_layer is not None:
                on_layer(layer, results[layer])
            if cache is not None and \
                    layer_sites[li] not in layer_sites[li + 1:]:
                # this layer's panel is done and no later layer shares
                # the site — release its activations/gradients so the
                # cache holds O(live sites), not O(L × dataset)
                cache.drop(layer_sites[li])
    return results


def auc_summary(results) -> Dict[str, float]:
    """Mean AUC per method across layers and runs (the reference's cell-11
    table, BASELINE.md row 'Layerwise robustness AUC')."""
    per_method: Dict[str, List[float]] = {}
    for layer in results.values():
        for method, runs in layer.items():
            per_method.setdefault(method, []).extend(r["auc"] for r in runs)
    return {m: float(np.mean(v)) for m, v in per_method.items()}


def auc_summary_std(results) -> Dict[str, Dict[str, float]]:
    """``{method: {"mean", "std", "n"}}`` over the per-run AUCs — the
    reference reports its AUC table as mean over 3 runs of the stochastic
    methods (BASELINE.md); this exposes the spread behind
    :func:`auc_summary`'s point estimate."""
    per_method: Dict[str, List[float]] = {}
    for layer in results.values():
        for method, runs in layer.items():
            per_method.setdefault(method, []).extend(r["auc"] for r in runs)
    return {
        m: {"mean": float(np.mean(v)), "std": float(np.std(v)),
            "n": len(v)}
        for m, v in per_method.items()
    }


def run_train_robustness(cfg, *, verbose: bool = True) -> Dict[str, float]:
    """The reference's full two-phase protocol as one command: train
    ``cfg.model`` on ``cfg.dataset`` (``run_train`` — epochs/optimizer/
    schedule from the same config), then run the layerwise-robustness
    sweep on the TRAINED weights.  This is the VGG-notebook recipe
    (pretrain → 15-layer × 8-method sweep) without a separate checkpoint
    hand-off; ``cfg.checkpoint_path`` still works for resuming the
    training phase."""
    from torchpruner_tpu.experiments.prune_retrain import (
        resolve_model_and_data,
    )
    from torchpruner_tpu.experiments.train_model import run_train

    # resolve ONCE and inject everywhere: run_train and the sweep would
    # otherwise each reload every split, and an injected trained model
    # with the default cfg.dataset would only be rejected AFTER the whole
    # training phase
    model, datasets = resolve_model_and_data(cfg, None, None)
    # resilient two-phase run: each phase journals into its OWN subdir
    # of run_dir (their manifests have different kinds — train vs
    # robustness — and must not collide)
    tcfg, scfg = cfg, cfg
    if cfg.run_dir:
        import dataclasses
        import os

        tcfg = dataclasses.replace(
            cfg, run_dir=os.path.join(cfg.run_dir, "train"))
        scfg = dataclasses.replace(
            cfg, run_dir=os.path.join(cfg.run_dir, "sweep"))
    trainer, history = run_train(
        tcfg, model=model, datasets=datasets, verbose=verbose
    )
    if cfg.run_dir:
        # a preempted train phase RETURNS like a finished one (that is
        # its contract) — but sweeping half-trained params would commit
        # wrong layer results into the sweep journal forever.  Only a
        # 'done' train manifest may proceed.
        from torchpruner_tpu.resilience.manifest import RunManifest

        if RunManifest.exists_in(tcfg.run_dir):
            tman = RunManifest.load(tcfg.run_dir)
            if tman.status != "done":
                if verbose:
                    print(
                        f"[{cfg.name}] training phase status "
                        f"{tman.status!r} — sweep NOT started (re-run "
                        f"with --resume {cfg.run_dir} to finish "
                        "training first)", flush=True,
                    )
                return {}
    if verbose and history:
        print(f"[{cfg.name}] trained: test acc "
              f"{history[-1]['test_acc']:.4f} — starting sweep",
              flush=True)
    return run_robustness_config(
        scfg, model=trainer.model, datasets=datasets,
        params=trainer.params, state=trainer.state, verbose=verbose,
    )


def run_robustness_config(cfg, *, model=None, datasets=None,
                          params=None, state=None,
                          verbose: bool = True) -> Dict[str, float]:
    """Config-driven sweep entry (the CLI's robustness path).

    ``cfg.method == "all"`` runs the reference's full method panel
    (6 metrics + signed Taylor + SV mean+2std — VGG notebook cell 8);
    otherwise just the configured method.  Returns the AUC summary.

    The reference sweep runs on a *pretrained* VGG16 (notebook cells 3-4);
    pass trained ``params``/``state``, or set ``cfg.checkpoint_path`` to a
    training checkpoint to restore it — a fresh init (the fallback) only
    makes sense for smoke runs, since method rankings on random weights
    are not the reference's experiment.
    """
    from torchpruner_tpu.core.segment import init_model
    from torchpruner_tpu.experiments.prune_retrain import (
        LOSS_REGISTRY,
        build_metric,
        filter_targets,
        resolve_model_and_data,
    )

    model, (_, _, test) = resolve_model_and_data(cfg, model, datasets)
    if len(test) > cfg.score_examples:
        test = test.subset(cfg.score_examples, seed=cfg.seed)
    if params is None and cfg.checkpoint_path:
        import os

        from torchpruner_tpu.checkpoint import restore_checkpoint

        if not os.path.exists(cfg.checkpoint_path):
            raise FileNotFoundError(
                f"cfg.checkpoint_path {cfg.checkpoint_path!r} does not "
                "exist — refusing to silently run the sweep on random "
                "weights (clear the field for an explicit fresh-init "
                "smoke run)"
            )
        model, params, state, _, _ = restore_checkpoint(cfg.checkpoint_path)
    if params is None:
        params, state = init_model(model, seed=cfg.seed)
    loss_fn = LOSS_REGISTRY[cfg.loss]
    score_dtype = (
        jnp.bfloat16 if cfg.score_dtype == "bfloat16" else None
    )

    # SPMD sweep (SURVEY.md §5.8): cfg.mesh shards the ablation batches and
    # the scoring rows over the data axis; a pod divides the 6.5 h-baseline
    # workload's wall-clock by the axis size.  Only a data axis helps here
    # (params are replicated — the sweep is evaluation, not training).
    mesh = None
    if cfg.mesh:
        if "data" not in cfg.mesh:
            raise ValueError(
                f"robustness sweep needs a 'data' axis to shard over, got "
                f"mesh={cfg.mesh!r} — the sweep is evaluation (params are "
                f"replicated), so only data parallelism applies; rename "
                f"the axis or clear cfg.mesh for a single-device run"
            )
        from torchpruner_tpu.parallel import make_mesh

        mesh = make_mesh(cfg.mesh)
    test_batches = test.batches(
        cfg.eval_batch_size, drop_remainder=mesh is not None
    )
    if mesh is not None and len(test) % cfg.eval_batch_size:
        # drop_remainder means the meshed run evaluates fewer examples
        # than a single-device run of the same config — surface it so
        # cross-configuration AUC comparisons are interpreted correctly.
        logging.getLogger("torchpruner_tpu").warning(
            "mesh sweep drops a %d-example tail (%d examples %% "
            "eval_batch_size %d); AUCs are comparable across mesh sizes "
            "with the same batch size, not against a single-device run "
            "that keeps the tail",
            len(test) % cfg.eval_batch_size, len(test),
            cfg.eval_batch_size,
        )

    def factory(method, reduction="mean", **kw):
        def make(run=0):
            metric = build_metric(
                method, model, params, test_batches, loss_fn, state=state,
                reduction=reduction, seed=cfg.seed + run,
                compute_dtype=score_dtype, **kw,
            )
            if mesh is not None:
                from torchpruner_tpu.parallel import DistributedScorer

                metric = DistributedScorer(metric, mesh)
            return metric
        return make

    if cfg.method == "all":
        methods = {
            "random": factory("random"),
            "weight_norm": factory("weight_norm"),
            "apoz": factory("apoz"),
            "sensitivity": factory("sensitivity"),
            "taylor": factory("taylor"),
            "taylor_signed": factory("taylor", signed=True),
            "sv": factory("shapley", **cfg.method_kwargs),
            "sv_mean+2std": factory(
                "shapley", reduction="mean+2std", **cfg.method_kwargs
            ),
        }
    else:
        methods = {
            cfg.method: factory(
                cfg.method, reduction=cfg.reduction, **cfg.method_kwargs
            )
        }
    layers = filter_targets(
        [g.target for g in pruning_graph(model)], cfg
    )
    # resumable sweep (cfg.run_dir / CLI --resume): completed layers'
    # results persist atomically per layer; a killed/preempted sweep
    # restarts at the first unfinished layer instead of hour zero
    journal = None
    on_layer = None
    if cfg.run_dir:
        from torchpruner_tpu.resilience.runner import SweepJournal

        journal = SweepJournal(cfg)
        on_layer = journal.on_layer
        done_layers = len(layers) - len(journal.remaining(layers))
        if verbose and journal.resuming:
            print(
                f"[{cfg.name}] resuming sweep: {done_layers}/"
                f"{len(layers)} layers already complete", flush=True,
            )
        layers = journal.remaining(layers)
    preempted = False
    try:
        results = layerwise_robustness(
            model, params, state, test_batches, methods, loss_fn,
            layers=layers,
            find_best_evaluation_layer_=cfg.find_best_evaluation_layer,
            mesh=mesh,
            compute_dtype=score_dtype,
            capture=cfg.capture,
            verbose=verbose,
            on_layer=on_layer,
        )
        if journal is not None:
            journal.done()
    except Exception as e:
        from torchpruner_tpu.resilience.guards import Preempted

        if journal is None or not isinstance(e, Preempted):
            raise
        # every completed layer is already on disk; report what we have
        results = {}
        preempted = True
        if verbose:
            print(
                f"[{cfg.name}] sweep preempted: "
                f"{len(journal.manifest.completed)} layers committed; "
                f"re-run with --resume {cfg.run_dir} to continue",
                flush=True,
            )
    finally:
        if journal is not None:
            journal.close()  # give the SIGTERM handler back, always
    if journal is not None:
        results = journal.merged(results)
    aucs = auc_summary(results)
    if preempted:
        # a half-finished sweep must NOT masquerade as a complete one:
        # no results_path / plot artifacts (the run-dir journal holds
        # the partials + a 'preempted' manifest); the partial summary is
        # returned for the resume message only
        return aucs
    if cfg.results_path:
        import json
        import os

        os.makedirs(os.path.dirname(cfg.results_path) or ".", exist_ok=True)

        def listify(r):
            return {
                k: (np.asarray(v).tolist() if isinstance(
                    v, (np.ndarray, jnp.ndarray)) else v)
                for k, v in r.items()
            }

        with open(cfg.results_path, "w") as f:
            json.dump({
                "config": cfg.name,
                "auc_summary": aucs,
                "results": {
                    layer: {m: [listify(r) for r in runs]
                            for m, runs in methods_.items()}
                    for layer, methods_ in results.items()
                },
            }, f)
        if verbose:
            print(f"[robustness] wrote results to {cfg.results_path}",
                  flush=True)
    if cfg.plot_dir:
        import os

        from torchpruner_tpu.utils.plotting import (
            plot_auc_summary,
            plot_robustness_curves,
        )

        os.makedirs(cfg.plot_dir, exist_ok=True)
        for layer in results:
            plot_robustness_curves(
                results, layer,
                save_path=os.path.join(
                    cfg.plot_dir, f"robustness_{layer.replace('/', '_')}.png"
                ),
            )
        plot_auc_summary(
            aucs, save_path=os.path.join(cfg.plot_dir, "auc_summary.png")
        )
        if verbose:
            print(f"[robustness] wrote figures to {cfg.plot_dir}",
                  flush=True)
    return aucs

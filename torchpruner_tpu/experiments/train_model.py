"""From-scratch training driver — the reference's missing piece.

The reference trains its pretrained CIFAR10-VGG16 (92.5 % test accuracy)
with a driver script that is *not in the repo*: only the ingredients exist —
SGD lr=0.05 momentum=0.9 wd=5e-4 with MultiStepLR milestones
[30,60,90,120,150] γ=0.5 (reference experiments/models/cifar10.py:94-99)
and flip+crop augmentation (cifar10.py:102-126).  ``run_train`` is that
driver: config-driven training with LR schedules, augmentation, shape-aware
checkpoint/resume, per-epoch CSV logging, and the native prefetch pipeline
feeding batches while the device computes.
"""

from __future__ import annotations

import os
import time
from typing import Optional, Tuple

import jax
import numpy as np

from torchpruner_tpu import obs
from torchpruner_tpu.checkpoint import restore_checkpoint, save_checkpoint
from torchpruner_tpu.core.segment import SegmentedModel
from torchpruner_tpu.data.native import (
    augment_batch,
    device_prefetch,
    prefetch_batches,
    shuffled_indices,
)
from torchpruner_tpu.train.logger import CSVLogger
from torchpruner_tpu.train.loop import Trainer, trainer_from_config
from torchpruner_tpu.utils.config import ExperimentConfig


def epoch_batches(dataset, cfg: ExperimentConfig, epoch: int):
    """One epoch's batch stream: native prefetch (background host gather)
    when enabled, with optional augmentation applied as batches arrive.

    Both paths draw the same splitmix64 shuffle, so prefetch on/off yields
    bit-identical batch streams — determinism never depends on whether the
    C++ library built."""
    seed = cfg.seed * 1000 + epoch
    if cfg.prefetch:
        stream = prefetch_batches(
            dataset, cfg.batch_size, shuffle=True, seed=seed,
        )
    else:
        idx = shuffled_indices(len(dataset), seed)
        stream = (
            (dataset.x[idx[i:i + cfg.batch_size]],
             dataset.y[idx[i:i + cfg.batch_size]])
            for i in range(0, len(dataset), cfg.batch_size)
        )
    if not cfg.augment:
        yield from stream
        return
    # border fill -mean/std where the dataset is standardized: matches the
    # reference's pad-raw-then-Normalize border statistics exactly
    from torchpruner_tpu.data.datasets import norm_zero

    fill = norm_zero(cfg.dataset)
    for b, (x, y) in enumerate(stream):
        # per-batch seed, same splitmix64 contract on both the native and
        # numpy augmentation paths — epoch streams are bit-reproducible
        # regardless of which one is in play
        yield augment_batch(x, seed=seed * 1_000_003 + b, fill=fill), y


def run_train(
    cfg: ExperimentConfig,
    *,
    model: Optional[SegmentedModel] = None,
    datasets=None,
    verbose: bool = True,
) -> Tuple[Trainer, list]:
    """Train ``cfg.model`` on ``cfg.dataset`` for ``cfg.epochs``.

    Resumes from ``cfg.checkpoint_path`` when a checkpoint exists (epoch
    count rides in the checkpoint's ``extra``); saves every
    ``cfg.checkpoint_every_epochs`` and at the end.  Returns the final
    trainer and the per-epoch history
    ``[{epoch, train_loss, test_loss, test_acc, seconds}, ...]``.

    With ``cfg.run_dir`` set the run is PREEMPTION-SAFE and delegates to
    :func:`torchpruner_tpu.resilience.runner.run_resilient_train`:
    manifest + digest-verified checkpoints every
    ``cfg.checkpoint_every_steps`` steps, SIGTERM snapshot-and-exit,
    mid-epoch restart at the exact data cursor, non-finite guard with
    rollback + LR backoff, and OOM retry with doubled ``accum_steps``
    (CLI: ``--resume DIR`` / ``--checkpoint-every N`` / ``--chaos``).
    """
    if cfg.run_dir:
        from torchpruner_tpu.resilience.runner import run_resilient_train

        return run_resilient_train(cfg, model=model, datasets=datasets,
                                   verbose=verbose)
    from torchpruner_tpu.experiments.prune_retrain import (
        LOSS_REGISTRY,
        make_optimizer,
        resolve_model_and_data,
    )

    if cfg.chaos:
        from torchpruner_tpu.resilience import chaos as _chaos

        _chaos.configure(cfg.chaos)
    model, (train, _val, test) = resolve_model_and_data(cfg, model, datasets)
    steps_per_epoch = max(1, len(train) // cfg.batch_size)
    tx = make_optimizer(cfg, steps_per_epoch=steps_per_epoch)
    loss_fn = LOSS_REGISTRY[cfg.loss]

    mesh = None
    data_size = 1
    if cfg.mesh:
        # SPMD training over the configured mesh (FSDP/TP placement,
        # optional ZeRO weight-update sharding) — same loop, distributed
        # placement; ragged tail batches that can't shard are dropped
        from torchpruner_tpu.parallel import make_mesh

        mesh = make_mesh(cfg.mesh)
        data_size = int(dict(mesh.shape).get("data", 1))

    start_epoch = 0
    if cfg.checkpoint_path and os.path.exists(cfg.checkpoint_path):
        model, params, state, opt_state, meta = restore_checkpoint(
            cfg.checkpoint_path, tx=tx
        )
        trainer = trainer_from_config(cfg, model, tx, loss_fn, mesh=mesh,
                                      params=params, state=state,
                                      opt_state=opt_state)
        start_epoch = int(meta.get("extra", {}).get("epoch", 0))
        if verbose:
            print(f"[{cfg.name}] resumed from {cfg.checkpoint_path} "
                  f"at epoch {start_epoch}", flush=True)
    else:
        trainer = trainer_from_config(cfg, model, tx, loss_fn, mesh=mesh)

    logger = CSVLogger(cfg.log_path, experiment=cfg.name)
    test_batches = test.batches(cfg.eval_batch_size)
    history = []
    for epoch in range(start_epoch, cfg.epochs):
        t0 = time.perf_counter()
        losses = []
        stream = epoch_batches(train, cfg, epoch)
        if cfg.device_prefetch:
            stream = device_prefetch(stream, size=cfg.device_prefetch)
        with obs.span("train", epoch=epoch):
            for x, y in stream:
                if data_size > 1 and x.shape[0] % data_size:
                    # the epoch's ragged tail can't shard over the data
                    # axis — drop it, counted (never silently)
                    obs.inc("mesh_ragged_drops_total",
                            help="tail batches dropped because they "
                                 "don't divide the mesh's data axis")
                    continue
                # keep the loss on device: a float() here would fence every
                # step and forfeit both async dispatch and the prefetch; the
                # periodic fence on a loss 8 steps back bounds dispatch
                # run-ahead (each in-flight step pins its batch in HBM)
                # without draining the pipeline
                losses.append(trainer.step(x, y))
                if len(losses) % 8 == 0:
                    jax.block_until_ready(losses[-8])
            losses = [float(l) for l in losses]  # full sync once per epoch
        with obs.span("eval", epoch=epoch):
            test_loss, test_acc = trainer.evaluate(test_batches)
        dt = time.perf_counter() - t0
        rec = {
            "epoch": epoch,
            "train_loss": float(np.mean(losses)) if losses else float("nan"),
            "test_loss": test_loss,
            "test_acc": test_acc,
            "seconds": dt,
        }
        history.append(rec)
        obs.record_epoch(**rec)
        logger.log_epoch(
            epoch=epoch, train_loss=rec["train_loss"],
            test_loss=test_loss, test_acc=test_acc, seconds=dt,
        )
        if verbose:
            print(
                f"[{cfg.name}] epoch {epoch}: train {rec['train_loss']:.4f} "
                f"test {test_loss:.4f} acc {test_acc:.4f} ({dt:.1f}s)",
                flush=True,
            )
        if cfg.checkpoint_path and (
            (cfg.checkpoint_every_epochs
             and (epoch + 1) % cfg.checkpoint_every_epochs == 0)
            or epoch + 1 == cfg.epochs
        ):
            save_checkpoint(
                cfg.checkpoint_path, trainer.model, trainer.params,
                trainer.state, trainer.opt_state,
                extra={"epoch": epoch + 1},
            )
    return trainer, history


def run_train_elastic(
    cfg: ExperimentConfig,
    *,
    max_restarts: int = 3,
    verbose: bool = True,
    **kw,
) -> Tuple[Trainer, list]:
    """:func:`run_train` with failure recovery — the checkpoint-restart
    elasticity long pod runs need (SURVEY.md §5.3: the reference has no
    failure handling at all; preemptions and transient device loss are
    normal on TPU fleets).

    A failing run restarts from the last on-disk checkpoint, up to
    ``max_restarts`` times; because :func:`run_train` already resumes
    from ``cfg.checkpoint_path``, recovery is a plain re-entry.  Requires
    ``cfg.checkpoint_path`` (without it a restart would silently retrain
    from scratch, which is worse than failing).  The returned history is
    the final successful attempt's (resume epoch onward); ``cfg.log_path``
    carries every completed epoch across attempts.
    """
    if not cfg.checkpoint_path:
        raise ValueError(
            "run_train_elastic needs cfg.checkpoint_path — recovery "
            "without a checkpoint would restart from scratch"
        )
    for attempt in range(max_restarts + 1):
        try:
            return run_train(cfg, verbose=verbose, **kw)
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:  # noqa: BLE001 - elastic by design
            if attempt == max_restarts:
                raise
            if verbose:
                print(
                    f"[{cfg.name}] attempt {attempt + 1} failed "
                    f"({type(e).__name__}: {e}); restarting from "
                    f"checkpoint", flush=True,
                )
    raise AssertionError("unreachable")

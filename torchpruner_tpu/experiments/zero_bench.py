"""ZeRO weight-update-sharding A/B bench: zero-vs-replicated on the
vgg16/llama train legs, plus the widened batch sweep the freed HBM buys.

For each model the SAME ``ShardedTrainer`` config runs twice — once
replicated (``zero=False``: optimizer state and the weight update
repeated on every data replica) and once with ``zero=True`` (gradients
reduce-scattered onto the data axis, the optax update applied to the
local 1/N shard, params all-gathered) — measuring:

- steady-state ms/step via ``multi_step`` (K steps per dispatched
  program, the same protocol as bench.py's train legs), after a
  parity check that the two trainers' losses agree;
- ``planned_opt_bytes_per_chip`` from ``parallel.memory.training_memory``
  for both placements — the acceptance invariant
  ``zero_opt <= replicated_opt / data_axis + const`` is asserted here;
- on TPU (non-smoke): the batch sweep ONE BUCKET past the vgg16/llama
  train legs' plateau (vgg16 to 4096, llama to 128), with MFU per
  point — the freed optimizer HBM is exactly what capped the r05 sweep.

Every number exports as a ``zero_*`` gauge into the active obs session,
so the rows land in ``report.json`` and ride ``obs diff --gate``
(dynamic scalar family, like ``kernel_*``); CI drives this module on an
8-virtual-device CPU and gates against
``results/obs_report_golden_zero_cpu.json``.

Run: ``python -m torchpruner_tpu.experiments.zero_bench [--smoke]
[--cpu] [--devices N] [--obs-dir DIR] [--out PATH]``.  ``--devices N``
forces N virtual host devices (CPU only; must be set before the backend
initializes, which is why this module never imports jax at module
scope).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

#: acceptance slack for the opt-bytes invariant: replicated non-param
#: leaves (step counters) plus per-leaf ceil-division padding
OPT_BYTES_SLACK = 1 << 16


def _make_mesh():
    import jax

    from torchpruner_tpu.parallel import make_mesh

    n = jax.device_count()
    if n < 2:
        raise RuntimeError(
            f"zero_bench needs >= 2 devices for a data axis (have {n}); "
            "on CPU pass --devices 8"
        )
    model_ax = 2 if n >= 4 and n % 2 == 0 else 1
    return make_mesh({"data": n // model_ax, "model": model_ax})


def _measure_pair(name, model_fn, batch, loss_fn, make_batch, mesh,
                  smoke: bool, out: dict):
    """One model's zero-vs-replicated A/B; mutates ``out[name]`` and
    exports the ``zero_<name>_*`` gauges."""
    import jax
    import numpy as np
    import optax

    from torchpruner_tpu import obs
    from torchpruner_tpu.parallel import ShardedTrainer, training_memory
    from torchpruner_tpu.utils.profiling import (
        steady_s,
        time_train_multi_step,
    )

    data_ax = int(dict(mesh.shape).get("data", 1))
    K = 2 if smoke else 4
    iters = 2 if smoke else 4
    x, y = make_batch(batch)
    xs = jax.numpy.stack([x] * K)
    ys = jax.numpy.stack([y] * K)

    trainers = {}
    for zero in (False, True):
        trainers[zero] = ShardedTrainer.create(
            model_fn(), optax.adam(1e-3), loss_fn, mesh, seed=0,
            zero=zero, compute_dtype=jax.numpy.bfloat16,
        )
    # parity before timing (doubles as warmup): the two placements must
    # walk the same trajectory at bf16/reduction-order tolerance
    for _ in range(2):
        l_rep = float(trainers[False].step(x, y))
        l_zero = float(trainers[True].step(x, y))
        np.testing.assert_allclose(l_rep, l_zero, rtol=1e-4, atol=1e-5)

    row = {"batch": batch, "parity_loss": round(l_rep, 5)}
    for zero in (False, True):
        stats = time_train_multi_step(trainers[zero], xs, ys, iters=iters,
                                      warmup=1, chained=True)
        key = "ms" if zero else "rep_ms"
        row[key] = round(steady_s(stats) / K * 1e3, 3)
        row[("compile_s" if zero else "rep_compile_s")] = round(
            stats["compile_s"], 2)
        budget = training_memory(
            trainers[zero].model, trainers[zero]._placements[0],
            dict(mesh.shape), tx=trainers[zero].tx,
            compute_dtype=jax.numpy.bfloat16,
            params=trainers[zero].params, zero=zero,
        )
        row["opt_mb" if zero else "rep_opt_mb"] = round(
            budget.opt_bytes / 2**20, 3)
        row["opt_bytes" if zero else "rep_opt_bytes"] = budget.opt_bytes
    row["step_speedup"] = round(row["rep_ms"] / row["ms"], 3) \
        if row["ms"] else None
    row["opt_ratio"] = round(row["opt_bytes"] / row["rep_opt_bytes"], 4) \
        if row["rep_opt_bytes"] else None
    # the acceptance invariant: ZeRO's persistent opt state is at most
    # the replicated bytes / data-axis size, plus replicated scalars
    assert row["opt_bytes"] <= row["rep_opt_bytes"] / data_ax \
        + OPT_BYTES_SLACK, (row, data_ax)
    out[name] = row
    for key in ("ms", "rep_ms", "step_speedup", "opt_mb", "rep_opt_mb",
                "opt_ratio"):
        if row.get(key) is not None:
            obs.gauge_set(f"zero_{name}_{key}", float(row[key]),
                          help="zero_bench zero-vs-replicated A/B")
    return trainers[True]


def _batch_sweep(name, trainer, make_batch, batches, K, out: dict):
    """Widened batch sweep on the ZeRO trainer (TPU full runs): ms/step,
    throughput and MFU per batch; an OOM records an error cell and ends
    the sweep (larger batches would only fail harder)."""
    import jax

    from torchpruner_tpu.utils.flops import model_cost, peak_bf16_flops
    from torchpruner_tpu.utils.profiling import (
        steady_s,
        time_train_multi_step,
    )

    peak = peak_bf16_flops(jax.devices()[0])
    sweep = {}
    for b in batches:
        try:
            x, y = make_batch(b)
            xs = jax.numpy.stack([x] * K)
            ys = jax.numpy.stack([y] * K)
            stats = time_train_multi_step(trainer, xs, ys, iters=3,
                                          warmup=1, chained=True)
            step_s = steady_s(stats) / K
            cell = {"ms": round(step_s * 1e3, 3),
                    "ex_per_s_per_chip": round(b / step_s, 1)}
            _, fwd = model_cost(trainer.model, trainer.params,
                               trainer.state, batch_size=b)
            if fwd and peak:
                cell["mfu"] = round((3.0 * fwd / step_s) / peak, 4)
            sweep[str(b)] = cell
        except Exception as e:  # noqa: BLE001 - OOM ends the sweep
            sweep[str(b)] = {"error": f"{type(e).__name__}: {e}"[:200]}
            break
    out[name]["batch_sweep"] = sweep
    best = max((v["mfu"] for v in sweep.values() if v.get("mfu")),
               default=None)
    if best is not None:
        out[name]["best_mfu"] = best
        from torchpruner_tpu import obs

        obs.gauge_set(f"zero_{name}_best_mfu", best,
                      help="best MFU over the widened zero batch sweep")


def run(smoke: bool = False, obs_dir: str | None = None) -> dict:
    import jax
    import numpy as np

    from torchpruner_tpu import obs
    from torchpruner_tpu.models import llama_tiny, mfu_llama, vgg16_bn
    from torchpruner_tpu.utils.losses import (
        cross_entropy_loss,
        lm_cross_entropy_loss,
    )

    session = obs.configure(obs_dir) if obs_dir else None
    try:
        with obs.span("zero_bench"):
            mesh = _make_mesh()
            data_ax = int(dict(mesh.shape).get("data", 1))
            on_tpu = jax.devices()[0].platform == "tpu"
            out = {
                "smoke": smoke,
                "platform": jax.devices()[0].platform,
                "devices": jax.device_count(),
                "mesh": dict(mesh.shape),
            }
            obs.gauge_set("zero_data_axis", float(data_ax),
                          help="data-axis size of the zero_bench mesh")
            rng = np.random.default_rng(0)

            if smoke:
                vgg_fn = lambda: vgg16_bn(width_multiplier=0.125,  # noqa: E731
                                          classifier_width=64)
                vgg_batch = 2 * data_ax
                llama_fn, llama_batch = llama_tiny, 2 * data_ax
            else:
                vgg_fn, vgg_batch = vgg16_bn, 256
                llama_fn, llama_batch = mfu_llama, 8

            def img_batch(b):
                return (
                    jax.numpy.asarray(
                        rng.normal(size=(b, 32, 32, 3)).astype("float32")),
                    jax.numpy.asarray(
                        rng.integers(0, 10, size=(b,)).astype("int32")),
                )

            S = llama_fn().input_shape[0]

            def tok_batch(b):
                t = jax.numpy.asarray(
                    rng.integers(0, 255, size=(b, S)).astype("int32"))
                return t, t

            t_vgg = _measure_pair("vgg", vgg_fn, vgg_batch,
                                  cross_entropy_loss, img_batch, mesh,
                                  smoke, out)
            t_llama = _measure_pair("llama", llama_fn, llama_batch,
                                    lm_cross_entropy_loss, tok_batch, mesh,
                                    smoke, out)
            if on_tpu and not smoke:
                # the point of the freed HBM: one bucket past the r05
                # plateau (vgg16 swept 512-2048, mfu_llama 16-64)
                _batch_sweep("vgg", t_vgg, img_batch, (1024, 2048, 4096),
                             K=4, out=out)
                _batch_sweep("llama", t_llama, tok_batch, (32, 64, 128),
                             K=4, out=out)
    finally:
        if session is not None:
            session.close()
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--devices", type=int, default=0,
                    help="force N virtual host devices (CPU)")
    ap.add_argument("--obs-dir", default="")
    ap.add_argument("--out", default="", help="also write the result JSON here")
    args = ap.parse_args(argv)
    if args.devices:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count="
                f"{args.devices}").strip()
    if args.cpu or args.devices:
        import jax

        jax.config.update("jax_platforms", "cpu")
    out = run(smoke=args.smoke, obs_dir=args.obs_dir or None)
    blob = json.dumps(out, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(blob)
    print(blob)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Experiment drivers — library versions of the reference's three notebooks
(SURVEY.md §2.8): the prune→fine-tune loop ("Pruning Untrained Networks")
and the layerwise-robustness ablation sweep (CIFAR-10 VGG16 notebook)."""

from torchpruner_tpu.experiments.prune_retrain import (
    build_metric,
    run_prune_retrain,
    METRIC_REGISTRY,
)
from torchpruner_tpu.experiments.robustness import (
    ablation_curve,
    layerwise_robustness,
    loss_increase_auc,
)
from torchpruner_tpu.experiments.train_model import run_train, run_train_elastic

__all__ = [
    "build_metric",
    "run_prune_retrain",
    "METRIC_REGISTRY",
    "ablation_curve",
    "layerwise_robustness",
    "loss_increase_auc",
    "run_train",
    "run_train_elastic",
]

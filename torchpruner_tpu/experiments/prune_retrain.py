"""The prune→fine-tune driver — the reference's core recipe as a library
function (reference "Pruning Untrained Networks.ipynb" cell 6 /
SURVEY.md §3.4): for each prunable layer, outermost first: score → turn
scores into indices (policy) → prune → evaluate (→ optionally fine-tune).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np
import optax

from torchpruner_tpu.attributions import (
    APoZAttributionMetric,
    RandomAttributionMetric,
    SensitivityAttributionMetric,
    ShapleyAttributionMetric,
    TaylorAttributionMetric,
    WeightNormAttributionMetric,
)
from torchpruner_tpu.core.graph import pruning_graph
from torchpruner_tpu.core.pruner import prune_by_scores
from torchpruner_tpu.data import load_dataset
from torchpruner_tpu.models import cifar10_fc, fmnist_convnet, mnist_fc, vgg16_bn
from torchpruner_tpu.train.logger import CSVLogger
from torchpruner_tpu.train.loop import Trainer, train_epoch
from torchpruner_tpu.utils.config import ExperimentConfig
from torchpruner_tpu.utils.flops import model_cost
from torchpruner_tpu.utils.losses import cross_entropy_loss
from torchpruner_tpu.utils.reductions import mean_plus_2std

METRIC_REGISTRY = {
    "random": RandomAttributionMetric,
    "weight_norm": WeightNormAttributionMetric,
    "apoz": APoZAttributionMetric,
    "sensitivity": SensitivityAttributionMetric,
    "taylor": TaylorAttributionMetric,
    "shapley": ShapleyAttributionMetric,
}

MODEL_REGISTRY = {
    "mnist_fc": (mnist_fc, "mnist_flat"),
    "cifar10_fc": (cifar10_fc, "cifar10_flat"),
    "fmnist_convnet": (fmnist_convnet, "fashion_mnist"),
    "vgg16_bn": (vgg16_bn, "cifar10"),
}


def build_metric(name: str, model, params, data, loss_fn, *, state=None,
                 reduction="mean", seed=0, **kwargs):
    """Metric factory; ``reduction`` accepts the named 'mean+2std'
    (the VGG notebook's custom reduction, BASELINE.md)."""
    if reduction == "mean+2std":
        reduction = mean_plus_2std
    cls = METRIC_REGISTRY[name]
    return cls(model, params, data, loss_fn, state=state,
               reduction=reduction, seed=seed, **kwargs)


def make_optimizer(cfg: ExperimentConfig):
    tx = optax.sgd(cfg.lr, momentum=cfg.momentum or None)
    if cfg.weight_decay:
        tx = optax.chain(optax.add_decayed_weights(cfg.weight_decay), tx)
    return tx


@dataclass
class PruneStepRecord:
    layer: str
    pre_loss: float
    pre_acc: float
    post_loss: float
    post_acc: float
    n_params: int
    n_dropped: int
    prune_time: float
    widths: Dict[str, int]


def run_prune_retrain(
    cfg: ExperimentConfig,
    *,
    model=None,
    datasets=None,
    verbose: bool = True,
) -> List[PruneStepRecord]:
    """Run the full prune(-retrain) experiment described by ``cfg``.

    ``model`` / ``datasets=(train, val, test)`` may be injected (tests,
    custom zoos); defaults come from the registries.
    """
    if model is None:
        model_fn, default_ds = MODEL_REGISTRY[cfg.model]
        model = model_fn()
    else:
        default_ds = cfg.dataset
    if datasets is None:
        ds_name = cfg.dataset if cfg.dataset != "synthetic" else default_ds
        train = load_dataset(ds_name, "train", seed=cfg.seed)
        val = load_dataset(ds_name, "val", n=cfg.score_examples, seed=cfg.seed)
        test = load_dataset(ds_name, "test", seed=cfg.seed)
    else:
        train, val, test = datasets

    tx = make_optimizer(cfg)
    trainer = Trainer.create(model, tx, cross_entropy_loss, seed=cfg.seed)
    logger = CSVLogger(cfg.log_path, experiment=cfg.name)
    history: List[PruneStepRecord] = []

    groups = list(pruning_graph(trainer.model))
    if cfg.prune_order == "reverse":
        groups = groups[::-1]  # outermost layer first (reference recipe)
    targets = [g.target for g in groups]

    val_batches = val.batches(cfg.eval_batch_size)
    test_batches = test.batches(cfg.eval_batch_size)

    for target in targets:
        metric = build_metric(
            cfg.method, trainer.model, trainer.params, val_batches,
            cross_entropy_loss, state=trainer.state,
            reduction=cfg.reduction, seed=cfg.seed, **cfg.method_kwargs,
        )
        t0 = time.perf_counter()
        scores = metric.run(
            target, find_best_evaluation_layer=cfg.find_best_evaluation_layer
        )
        pre_loss, pre_acc = trainer.evaluate(test_batches)
        res = prune_by_scores(
            trainer.model, trainer.params, target, scores,
            policy=cfg.policy, fraction=cfg.fraction,
            state=trainer.state, opt_state=trainer.opt_state,
        )
        prune_time = time.perf_counter() - t0
        n_dropped = trainer.model.layer(target).features - res.model.layer(
            target
        ).features
        trainer = trainer.rebuild(res.model, res.params, res.state, res.opt_state)

        for epoch in range(cfg.finetune_epochs):
            train_epoch(
                trainer, train.batches(cfg.batch_size, shuffle=True,
                                       seed=cfg.seed + epoch),
                epoch=epoch, verbose=False,
            )

        post_loss, post_acc = trainer.evaluate(test_batches)
        n_params, flops = model_cost(trainer.model, trainer.params, trainer.state)
        rec = PruneStepRecord(
            layer=target, pre_loss=pre_loss, pre_acc=pre_acc,
            post_loss=post_loss, post_acc=post_acc, n_params=n_params,
            n_dropped=n_dropped, prune_time=prune_time,
            widths=trainer.model.widths(),
        )
        history.append(rec)
        logger.log_prune_step(
            layer=target, method=cfg.method,
            test_loss=pre_loss, test_acc=pre_acc,
            test_loss_pp=post_loss, test_acc_pp=post_acc,
            n_params=n_params, flops=flops, widths=rec.widths,
            prune_time=prune_time,
        )
        if verbose:
            print(
                f"[{cfg.name}] pruned {n_dropped} units from {target}: "
                f"acc {pre_acc:.4f}→{post_acc:.4f}, params {n_params}",
                flush=True,
            )
    return history

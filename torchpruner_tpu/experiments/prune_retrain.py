"""The prune→fine-tune driver — the reference's core recipe as a library
function (reference "Pruning Untrained Networks.ipynb" cell 6 /
SURVEY.md §3.4): for each prunable layer, outermost first: score → turn
scores into indices (policy) → prune → evaluate (→ optionally fine-tune).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np
import optax

from torchpruner_tpu import obs
from torchpruner_tpu.attributions import (
    APoZAttributionMetric,
    RandomAttributionMetric,
    SensitivityAttributionMetric,
    ShapleyAttributionMetric,
    TaylorAttributionMetric,
    WeightNormAttributionMetric,
)
from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.graph import pruning_graph
from torchpruner_tpu.core.pruner import prune, score_drop_indices
from torchpruner_tpu.data import load_dataset
from torchpruner_tpu.models import (
    bert_base,
    bert_tiny,
    cifar10_fc,
    digits_convnet,
    digits_fc,
    fc_net,
    fmnist_convnet,
    llama3_8b,
    llama_tiny,
    mfu_llama,
    mnist_fc,
    resnet20_cifar,
    resnet50,
    vgg16_bn,
    vit_b16,
    vit_tiny,
)
from torchpruner_tpu.train.logger import CSVLogger
from torchpruner_tpu.train.loop import Trainer, train_epoch
from torchpruner_tpu.utils.config import ExperimentConfig
from torchpruner_tpu.utils.flops import model_cost
from torchpruner_tpu.utils.losses import (
    cross_entropy_loss,
    lm_cross_entropy_loss,
    mse_loss,
    nll_loss,
)
from torchpruner_tpu.utils.reductions import mean_plus_2std

METRIC_REGISTRY = {
    "random": RandomAttributionMetric,
    "weight_norm": WeightNormAttributionMetric,
    "apoz": APoZAttributionMetric,
    "sensitivity": SensitivityAttributionMetric,
    "taylor": TaylorAttributionMetric,
    "shapley": ShapleyAttributionMetric,
}

#: model name -> (constructor, default dataset).  Reference-parity models
#: plus the BASELINE.json capability families and their tiny smoke variants.
MODEL_REGISTRY = {
    "mnist_fc": (mnist_fc, "mnist_flat"),
    "cifar10_fc": (cifar10_fc, "cifar10_flat"),
    "digits_fc": (digits_fc, "digits_flat"),
    "digits_fc_tiny": (
        # 64-64-64-10: the reference MLP recipe at quick-lane scale
        # (mnist_mlp_shapley --smoke / the obs CLI smoke test)
        lambda: fc_net(64, hidden=(64, 64)),
        "digits_flat",
    ),
    "digits_convnet": (digits_convnet, "digits"),
    "fmnist_convnet": (fmnist_convnet, "fashion_mnist"),
    "vgg16_bn": (vgg16_bn, "cifar10"),
    "vgg16_bn_tiny": (
        lambda: vgg16_bn(width_multiplier=0.125, classifier_width=64),
        "cifar10",
    ),
    "resnet50": (resnet50, "imagenet"),
    "resnet20_cifar": (resnet20_cifar, "cifar10"),
    "vit_b16": (vit_b16, "imagenet"),
    "vit_tiny": (vit_tiny, "tiny_images16"),
    "bert_base": (bert_base, "glue_sst2"),
    "bert_tiny": (bert_tiny, "glue_tiny"),
    "llama3_8b": (llama3_8b, "lm_corpus"),
    "llama_tiny": (llama_tiny, "lm_tiny"),
    "mfu_llama": (mfu_llama, "lm_mfu"),
}

LOSS_REGISTRY = {
    "cross_entropy": cross_entropy_loss,
    "lm_cross_entropy": lm_cross_entropy_loss,
    "nll": nll_loss,
    "mse": mse_loss,
}


def build_metric(name: str, model, params, data, loss_fn, *, state=None,
                 reduction="mean", seed=0, **kwargs):
    """Metric factory; ``reduction`` accepts the named 'mean+2std'
    (the VGG notebook's custom reduction, BASELINE.md)."""
    if reduction == "mean+2std":
        reduction = mean_plus_2std
    cls = METRIC_REGISTRY[name]
    return cls(model, params, data, loss_fn, state=state,
               reduction=reduction, seed=seed, **kwargs)


def resolve_model_and_data(cfg: ExperimentConfig, model=None, datasets=None):
    """Shared experiment setup: registry lookups with injection overrides.
    Returns ``(model, (train, val, test))``."""
    if model is None:
        model_fn, default_ds = MODEL_REGISTRY[cfg.model]
        model = model_fn()
        ds_name = cfg.dataset if cfg.dataset != "synthetic" else default_ds
    else:
        if datasets is None and cfg.dataset == "synthetic":
            raise ValueError(
                "injecting a model requires an explicit cfg.dataset (or "
                "injected datasets) — 'synthetic' has no shape to infer"
            )
        ds_name = cfg.dataset
    if datasets is None:
        train = load_dataset(ds_name, "train", seed=cfg.seed)
        val = load_dataset(ds_name, "val", n=cfg.score_examples, seed=cfg.seed)
        test = load_dataset(ds_name, "test", seed=cfg.seed)
        datasets = (train, val, test)
    return model, datasets


def filter_targets(targets, cfg: ExperimentConfig):
    """Apply ``cfg.target_filter`` (substring match; empty = keep all)."""
    if not cfg.target_filter:
        return list(targets)
    return [t for t in targets if any(s in t for s in cfg.target_filter)]


def policy_for_target(cfg: ExperimentConfig, target: str):
    """``(policy, fraction)`` for one prune target: a
    ``cfg.layer_fractions`` substring match (first match wins, insertion
    order) forces the fraction policy at the mapped per-layer ratio;
    otherwise the config's global policy/fraction apply.  The one place
    the per-layer sparsity-search axis resolves, shared by the real and
    simulated prune paths so provenance can never disagree."""
    for key, frac in (cfg.layer_fractions or {}).items():
        if key in target:
            return "fraction", float(frac)
    return cfg.policy, cfg.fraction


def make_lr_schedule(cfg: ExperimentConfig, steps_per_epoch: int = 1,
                     total_epochs: Optional[int] = None):
    """``cfg.lr_schedule`` as an optax schedule (or the constant lr).

    Milestones/epoch counts are in *epochs* (matching the reference's
    MultiStepLR, cifar10.py:94-99); ``steps_per_epoch`` converts them to the
    optimizer's step domain.  ``total_epochs`` sizes the decaying schedules
    — callers whose optimizer survives several fine-tune passes (the
    prune-retrain loop carries opt_state across all prune targets) must
    pass the *whole run's* epoch count, or every pass after the first
    would sit at the decayed floor.
    """
    spe = max(1, steps_per_epoch)
    if cfg.lr_schedule == "constant":
        return cfg.lr
    if cfg.lr_schedule == "multistep":
        return optax.piecewise_constant_schedule(
            cfg.lr, {int(m) * spe: cfg.lr_gamma for m in cfg.lr_milestones}
        )
    if total_epochs is None:
        total_epochs = cfg.epochs or cfg.finetune_epochs or 1
    total = max(1, total_epochs) * spe
    if cfg.lr_schedule == "cosine":
        return optax.cosine_decay_schedule(cfg.lr, decay_steps=total)
    # warmup_cosine
    warmup = cfg.lr_warmup_epochs * spe
    return optax.warmup_cosine_decay_schedule(
        0.0, cfg.lr, warmup_steps=max(1, warmup),
        decay_steps=max(total, warmup + 1),
    )


def make_optimizer(cfg: ExperimentConfig, steps_per_epoch: int = 1,
                   total_epochs: Optional[int] = None):
    lr = make_lr_schedule(cfg, steps_per_epoch, total_epochs)
    if cfg.optimizer == "adam":
        return optax.adam(lr)
    if cfg.optimizer == "adamw":
        return optax.adamw(lr, weight_decay=cfg.weight_decay)
    tx = optax.sgd(lr, momentum=cfg.momentum or None)
    if cfg.weight_decay:
        tx = optax.chain(optax.add_decayed_weights(cfg.weight_decay), tx)
    return tx


@dataclass
class PruneStepRecord:
    layer: str
    pre_loss: float
    pre_acc: float
    post_loss: float
    post_acc: float
    n_params: int
    n_dropped: int
    prune_time: float
    widths: Dict[str, int]


def run_prune_retrain(
    cfg: ExperimentConfig,
    *,
    model=None,
    datasets=None,
    verbose: bool = True,
) -> List[PruneStepRecord]:
    """Run the full prune(-retrain) experiment described by ``cfg``.

    ``model`` / ``datasets=(train, val, test)`` may be injected (tests,
    custom zoos); defaults come from the registries.

    Telemetry: when an obs session is active (``obs.configure``), the run
    emits nested phase spans — setup → per-target attribution / eval /
    prune (plan, apply_plan) / shard / retrain — and the CSV rows carry
    the active span id for offline joins.
    """
    obs.annotate_run(experiment=cfg.name, kind="prune_retrain",
                     model=cfg.model, method=cfg.method, policy=cfg.policy)
    with obs.span("prune_retrain", experiment=cfg.name):
        return _run_prune_retrain(cfg, model=model, datasets=datasets,
                                  verbose=verbose)


def _run_prune_retrain(
    cfg: ExperimentConfig,
    *,
    model=None,
    datasets=None,
    verbose: bool = True,
) -> List[PruneStepRecord]:
    with obs.span("setup"):
        model, (train, val, test) = resolve_model_and_data(
            cfg, model, datasets)

        groups = list(pruning_graph(model))
        if cfg.prune_order == "reverse":
            groups = groups[::-1]  # outermost layer first (reference recipe)
        targets = filter_targets([g.target for g in groups], cfg)

        journal = guard = None
        if cfg.chaos:
            from torchpruner_tpu.resilience import chaos as _chaos

            _chaos.configure(cfg.chaos)
        if cfg.guard_nonfinite:
            from torchpruner_tpu.resilience import StepGuard

            guard = StepGuard(cfg.max_bad_steps)
        if cfg.run_dir:
            from torchpruner_tpu.resilience.runner import PruneJournal

            journal = PruneJournal(cfg)

        # a resumed run re-enters at the journal's (possibly
        # OOM-degraded) accumulation factor, not the config's
        accum_steps = (journal.manifest.accum_steps
                       if journal is not None
                       and journal.manifest.accum_steps
                       else cfg.accum_steps)
        spe = max(1, len(train) // cfg.batch_size)
        total_ft_epochs = cfg.finetune_epochs * max(1, len(targets))

        def build_tx():
            # one opt_state spans every target's fine-tune pass, so
            # decaying schedules must be sized for the whole run, not one
            # pass.  In a resilient run the LR-backoff stage rides along
            # (empty state, so the opt-state treedef survives rollbacks).
            if journal is not None:
                from torchpruner_tpu.resilience.runner import (
                    scaled_optimizer,
                )

                return scaled_optimizer(cfg, spe, journal.lr_scale,
                                        total_epochs=total_ft_epochs)
            return make_optimizer(cfg, steps_per_epoch=spe,
                                  total_epochs=total_ft_epochs)

        tx = build_tx()
        loss_fn = LOSS_REGISTRY[cfg.loss]
        import jax.numpy as jnp

        cdtype = jnp.bfloat16 if cfg.compute_dtype == "bfloat16" else None
        mesh = None
        if cfg.mesh:
            # SPMD loop: sharded training over the configured mesh and
            # data-parallel scoring over its data axis (SURVEY.md §5.8)
            from torchpruner_tpu.parallel import ShardedTrainer, make_mesh

            mesh = make_mesh(cfg.mesh)
            trainer = ShardedTrainer.create(
                model, tx, loss_fn, mesh, seed=cfg.seed,
                partition=cfg.partition, zero=cfg.zero,
                compute_dtype=cdtype,
                remat=cfg.remat, accum_steps=accum_steps,
                moe_aux_weight=cfg.moe_aux_weight,
                grad_norm=cfg.obs_grad_norm, guard=guard,
            )
        else:
            trainer = Trainer.create(
                model, tx, loss_fn, seed=cfg.seed,
                compute_dtype=cdtype, remat=cfg.remat,
                accum_steps=accum_steps,
                moe_aux_weight=cfg.moe_aux_weight,
                grad_norm=cfg.obs_grad_norm, guard=guard,
            )
        _configure_mfu(cfg, trainer)
    logger = CSVLogger(cfg.log_path, experiment=cfg.name)
    history: List[PruneStepRecord] = []
    if journal is not None and journal.resuming:
        from torchpruner_tpu.resilience.runner import rng_from_list

        with obs.span("resume"):
            m2, p2, s2, o2, meta = journal.restore(tx)
            trainer = trainer.rebuild(m2, p2, s2, o2)
            rng = meta.get("extra", {}).get("rng")
            if rng is not None:
                trainer.rng = rng_from_list(rng)
            trainer.step_count = int(meta.get("step", 0))
            history = [PruneStepRecord(**r) for r in journal.records()]
            # ledger continuity across the kill: rounds the manifest
            # committed are rehydrated into the ledger (deduped — a
            # reused obs dir already holds them; a fresh one gets them
            # backfilled), so the resumed run reports ONE run's rounds
            obs.ledger_backfill(journal.records())
        _configure_mfu(cfg, trainer)
        if verbose:
            print(
                f"[{cfg.name}] resumed prune-retrain from "
                f"{journal.manifest.checkpoint}: "
                f"{len(journal.completed)}/{len(targets)} targets done",
                flush=True,
            )

    # sharded paths split batches over the data axis — remainder batches
    # can't shard (sharding.shard_batch contract), so mesh mode drops them
    drop = mesh is not None
    val_batches = val.batches(cfg.eval_batch_size, drop_remainder=drop)
    test_batches = test.batches(cfg.eval_batch_size)

    score_dtype = jnp.bfloat16 if cfg.score_dtype == "bfloat16" else None

    def _restore_to(trainer, tx):
        """Roll the trainer back to the journal's committed checkpoint
        under a (possibly rebuilt) optimizer — rebuild() recompiles at
        the restored shapes with the trainer's current accum/guard."""
        from torchpruner_tpu.resilience.runner import rng_from_list

        m2, p2, s2, o2, meta = journal.restore(tx)
        trainer.tx = tx
        trainer._step_fn = None
        t = trainer.rebuild(m2, p2, s2, o2)
        rng = meta.get("extra", {}).get("rng")
        if rng is not None:
            t.rng = rng_from_list(rng)
        t.step_count = int(meta.get("step", 0))
        if guard is not None:
            guard.reset()
        return t

    def _run_target(target):
        nonlocal trainer
        stage = journal.stage_for(target) if journal is not None else None
        if stage is None:
            with obs.span("attribution", target=target, method=cfg.method):
                metric = build_metric(
                    cfg.method, trainer.model, trainer.params, val_batches,
                    loss_fn, state=trainer.state,
                    reduction=cfg.reduction, seed=cfg.seed,
                    compute_dtype=score_dtype, **cfg.method_kwargs,
                )
                t0 = time.perf_counter()
                if mesh is not None and "data" in cfg.mesh:
                    from torchpruner_tpu.parallel import DistributedScorer

                    scorer = DistributedScorer(metric, mesh)
                else:
                    scorer = metric
                scores = scorer.run(
                    target,
                    find_best_evaluation_layer=(
                        cfg.find_best_evaluation_layer),
                )
            with obs.span("eval", target=target, which="pre"):
                pre_loss, pre_acc = trainer.evaluate(test_batches)
            # ONE policy evaluation feeds the real prune, the simulated
            # prune, AND the ledger's decision/margin record, so the
            # provenance can never disagree with what was removed
            policy, fraction = policy_for_target(cfg, target)
            drop_idx = score_drop_indices(
                scores, policy=policy, fraction=fraction,
                bucket=cfg.bucket,
            )
            score_dist = obs.score_distribution(scores, drop_idx)
            if cfg.simulate:
                # mask the same slices a real prune would remove — shapes
                # (and compiled programs) never change across the sweep
                from torchpruner_tpu.core.masking import (
                    apply_masks,
                    drop_masks,
                )

                with obs.span("prune", target=target, simulate=True):
                    obs.record_prune(
                        target, drop_idx,
                        L.n_units(trainer.model.layer(target)),
                        simulate=True)
                    pm, sm = drop_masks(
                        trainer.model, trainer.params, {target: drop_idx},
                        state=trainer.state,
                    )
                    trainer.params = apply_masks(trainer.params, pm)
                    if trainer.state:
                        trainer.state = apply_masks(trainer.state, sm)
                prune_time = time.perf_counter() - t0
                n_dropped = len(drop_idx)
            else:
                with obs.span("prune", target=target):
                    res = prune(
                        trainer.model, trainer.params, target, drop_idx,
                        state=trainer.state, opt_state=trainer.opt_state,
                    )
                    prune_time = time.perf_counter() - t0
                    n_dropped = L.n_units(
                        trainer.model.layer(target)
                    ) - L.n_units(res.model.layer(target))
                    # rebuild recompiles at the new shapes (ShardedTrainer
                    # re-places under its own "shard" span)
                    trainer = trainer.rebuild(res.model, res.params,
                                              res.state, res.opt_state)
                _configure_mfu(cfg, trainer)
                if journal is not None:
                    # the mid-round anchor: prune applied, retrain not
                    # started — a kill during fine-tune resumes HERE
                    journal.pruned(trainer, target, {
                        "pre_loss": float(pre_loss),
                        "pre_acc": float(pre_acc),
                        "n_dropped": int(n_dropped),
                        "prune_time": float(prune_time),
                        # the scores die with this process — stage the
                        # distribution so a kill-then-resume round record
                        # still carries its decision margins
                        "score_dist": score_dist,
                    })
            epoch_i = 0
        else:
            # resumed mid-round: the restored checkpoint already holds the
            # pruned shapes; skip scoring/prune, finish the retrain (the
            # scores are gone with the killed process — the round record
            # carries the stage's decision stats without a distribution)
            pre_loss = float(stage["pre_loss"])
            pre_acc = float(stage["pre_acc"])
            n_dropped = int(stage["n_dropped"])
            prune_time = float(stage["prune_time"])
            epoch_i = int(stage.get("retrain_epoch", 0))
            score_dist = stage.get("score_dist")

        while True:
            try:
                with obs.span("retrain", target=target,
                              epochs=cfg.finetune_epochs):
                    while epoch_i < cfg.finetune_epochs:
                        # OOM-degraded accumulation can't split a ragged
                        # tail batch (step_accum raises on it) — drop
                        # and count the tail, same policy as the train
                        # runner's degraded path
                        drop_now = drop or trainer.accum_steps > 1
                        if (drop_now and not drop
                                and len(train) % cfg.batch_size):
                            obs.inc(
                                "resilience_ragged_drops_total",
                                help="tail batches dropped because "
                                     "they don't divide the degraded "
                                     "accum_steps")
                        train_epoch(
                            trainer,
                            train.batches(cfg.batch_size, shuffle=True,
                                          seed=cfg.seed + epoch_i,
                                          drop_remainder=drop_now),
                            epoch=epoch_i, verbose=False,
                        )
                        epoch_i += 1
                        if journal is not None:
                            journal.retrain_epoch_done(trainer, target,
                                                       epoch_i)
                            # snapshot-on-preempt must carry the TRUE
                            # position of the trainer it checkpoints
                            journal.check_preempt(
                                trainer,
                                stage=dict(journal.manifest.stage,
                                           retrain_epoch=epoch_i))
                break
            except NonFiniteStreakError as e:
                if journal is None or cfg.simulate:
                    raise
                journal.on_streak(e)  # budget check + LR backoff
                trainer = _restore_to(trainer, build_tx())
                st = journal.manifest.stage
                epoch_i = (int(st.get("retrain_epoch", 0))
                           if st.get("target") == target else 0)
                if verbose:
                    print(
                        f"[{cfg.name}] non-finite streak in {target} "
                        f"retrain: rolled back, lr_scale -> "
                        f"{journal.lr_scale:g}", flush=True,
                    )
            except Exception as e:  # noqa: BLE001 - classified below
                from torchpruner_tpu.resilience import is_oom_error
                from torchpruner_tpu.resilience.guards import (
                    next_accum_for_oom,
                )

                if journal is None or cfg.simulate or not is_oom_error(e):
                    raise
                new_accum = next_accum_for_oom(trainer.accum_steps,
                                               cfg.batch_size)
                if new_accum is None:
                    raise
                obs.inc("resilience_oom_retries_total",
                        help="OOM recoveries via doubled accum_steps")
                trainer.accum_steps = new_accum
                trainer = _restore_to(trainer, build_tx())
                st = journal.manifest.stage
                epoch_i = (int(st.get("retrain_epoch", 0))
                           if st.get("target") == target else 0)
                if verbose:
                    print(
                        f"[{cfg.name}] OOM in {target} retrain: rolled "
                        f"back with accum_steps={new_accum}", flush=True,
                    )

        with obs.span("eval", target=target, which="post"):
            post_loss, post_acc = trainer.evaluate(test_batches)
        with obs.span("flops", target=target):
            n_params, flops = model_cost(trainer.model, trainer.params,
                                         trainer.state)
        rec = PruneStepRecord(
            layer=target, pre_loss=pre_loss, pre_acc=pre_acc,
            post_loss=post_loss, post_acc=post_acc, n_params=n_params,
            n_dropped=n_dropped, prune_time=prune_time,
            widths=trainer.model.widths(),
        )
        history.append(rec)
        obs.record_round(
            target=target, round=len(history) - 1, method=cfg.method,
            policy=cfg.policy, n_dropped=int(n_dropped),
            simulate=bool(cfg.simulate), score_dist=score_dist,
            pre={"loss": float(pre_loss), "acc": float(pre_acc)},
            post={"loss": float(post_loss), "acc": float(post_acc)},
            params=int(n_params), flops=flops, widths=rec.widths,
            prune_time=float(prune_time),
            runtime=obs.runtime_snapshot(),
        )
        if journal is not None:
            import dataclasses as _dc

            journal.round_done(trainer, target, _dc.asdict(rec))
        logger.log_prune_step(
            layer=target, method=cfg.method,
            test_loss=pre_loss, test_acc=pre_acc,
            test_loss_pp=post_loss, test_acc_pp=post_acc,
            n_params=n_params, flops=flops, widths=rec.widths,
            prune_time=prune_time,
        )
        if verbose:
            print(
                f"[{cfg.name}] pruned {n_dropped} units from {target}: "
                f"acc {pre_acc:.4f}→{post_acc:.4f}, params {n_params}",
                flush=True,
            )

    from torchpruner_tpu.resilience.guards import (
        NonFiniteStreakError,
        Preempted,
    )

    try:
        for target in targets:
            if journal is not None:
                if target in journal.completed:
                    continue
                journal.check_preempt(trainer)
            _run_target(target)
        if journal is not None:
            journal.done()
    except Preempted:
        if verbose:
            print(
                f"[{cfg.name}] preempted: manifest committed "
                f"({len(journal.completed)}/{len(targets)} targets); "
                f"re-run with --resume {cfg.run_dir} to continue",
                flush=True,
            )
    finally:
        # every exit path (done, preempted, crashed) must give the
        # SIGTERM handler back — a leaked handler makes the rest of the
        # process silently ignore preemption notices
        if journal is not None:
            journal.close()
        logger.close()
    return history


def _configure_mfu(cfg: ExperimentConfig, trainer):
    """Point the obs step telemetry at the CURRENT model's training FLOPs
    (3× a forward at the training batch size — re-aimed after every prune,
    since the denominator shrinks with the model).  Costs one cost-analysis
    compile, so it only runs while a session is active."""
    if obs.get() is None:
        return
    try:
        _, fwd = model_cost(trainer.model, trainer.params, trainer.state,
                            batch_size=cfg.batch_size)
        if fwd:
            obs.configure_step_flops(
                flops_per_step=obs.train_flops_per_step(fwd))
    except Exception:
        pass

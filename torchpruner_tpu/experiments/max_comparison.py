"""Attribution-methods comparison on the analytic max model — the
reference's first notebook ("Attributions comparison (Max model).ipynb",
SURVEY.md §2.8): compute every metric side by side on the 2→4→1 net whose
ground-truth unit relevances are derivable by hand, and report them next to
the analytic values.

The reference notebook re-implements each method in raw torch with a
20k-permutation Shapley loop; here the same table falls out of the library's
own metrics (which is the point: the library reproduces the paper's Fig. 1
numbers through its public API).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from torchpruner_tpu.attributions import (
    APoZAttributionMetric,
    SensitivityAttributionMetric,
    ShapleyAttributionMetric,
    TaylorAttributionMetric,
    WeightNormAttributionMetric,
)
from torchpruner_tpu.models.analytic import max_model, max_model_batches
from torchpruner_tpu.utils.losses import mse_loss

#: analytic ground truths (reference tests/test_attributions.py:93-137 and
#: models/analytic.py docstring), version-1 weights
GROUND_TRUTH = {
    "weight_norm": [1.0, 2.0, 2.0, 2.0],
    "apoz": [0.5, 0.5, 1.0, 1.0],
    "sensitivity": [0.0, 0.0, 0.0, 0.0],
    "taylor": [0.0, 0.0, 0.0, 0.0],
    "shapley": [0.37, 0.37, 1.7, 0.0],
}


def run_max_comparison(
    version: int = 1, sv_samples: int = 1000, seed: int = 0,
    verbose: bool = True,
) -> Dict[str, np.ndarray]:
    """Score units A-D of the max model with every metric.

    Returns ``{method: (4,) scores}``; with ``version=1`` the values match
    :data:`GROUND_TRUTH` (Shapley statistically, at ``sv_samples=1000`` to
    ~1 decimal — the reference's own test tolerance,
    test_attributions.py:128-137).
    """
    model, params, _, _ = max_model(version)
    data = max_model_batches()
    common = dict(state=None, reduction="mean", seed=seed)
    metrics = {
        "weight_norm": WeightNormAttributionMetric(
            model, params, data, mse_loss, **common),
        "apoz": APoZAttributionMetric(model, params, data, mse_loss, **common),
        "sensitivity": SensitivityAttributionMetric(
            model, params, data, mse_loss, **common),
        "taylor": TaylorAttributionMetric(
            model, params, data, mse_loss, **common),
        "shapley": ShapleyAttributionMetric(
            model, params, data, mse_loss, sv_samples=sv_samples, **common),
    }
    results = {}
    for name, metric in metrics.items():
        results[name] = np.asarray(
            metric.run("fc1", find_best_evaluation_layer=True)
        )
    if verbose:
        units = ["A", "B", "C", "D"]
        print(f"{'method':14s} " + " ".join(f"{u:>7s}" for u in units)
              + ("   (analytic)" if version == 1 else ""))
        for name, vals in results.items():
            row = f"{name:14s} " + " ".join(f"{v:7.3f}" for v in vals)
            if version == 1 and name in GROUND_TRUTH:
                row += "   " + str(GROUND_TRUTH[name])
            print(row)
    return results


if __name__ == "__main__":
    run_max_comparison()

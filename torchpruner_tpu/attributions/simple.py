"""Data-free metrics: Random and WeightNorm.

Reference: torchpruner/attributions/methods/random.py and weight_norm.py.
"""

from __future__ import annotations

import jax
import numpy as np
import jax.numpy as jnp

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.attributions.base import AttributionMetric, param_at


class RandomAttributionMetric(AttributionMetric):
    """Uniform random scores; the control baseline (reference random.py:5-13).

    Randomness flows through an explicit PRNG key (deterministic given
    ``seed``; a fresh subkey per call)."""

    shiftable = False
    data_dependent = False  # no forwards: capture-cache-neutral

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._calls = 0

    def run(self, layer, *, find_best_evaluation_layer=False, **kw):
        spec = self.model.layer(layer)
        n = L.n_units(spec)
        self._calls += 1
        key = jax.random.fold_in(jax.random.PRNGKey(self.seed), self._calls)
        return np.asarray(jax.random.uniform(key, (n,)))


class WeightNormAttributionMetric(AttributionMetric):
    """L1 norm of each unit's incoming weights (Li et al., ICLR 2017;
    reference weight_norm.py:13-19: abs then sum all non-out axes)."""

    shiftable = False
    data_dependent = False  # weight-only: capture-cache-neutral

    def run(self, layer, *, find_best_evaluation_layer=False, **kw):
        spec = self.model.layer(layer)
        p = param_at(self.params, layer)
        if isinstance(spec, L.Dense):  # (in, out)
            return np.asarray(jnp.abs(p["w"]).sum(axis=0))
        if isinstance(spec, L.Conv):  # HWIO
            return np.asarray(jnp.abs(p["w"]).sum(axis=(0, 1, 2)))
        if isinstance(spec, L.GatedDense):  # gate + up, per hidden channel
            return np.asarray(
                jnp.abs(p["wg"]).sum(axis=0) + jnp.abs(p["wu"]).sum(axis=0)
            )
        if isinstance(spec, L.MultiHeadAttention):
            # per query head: incoming |wq| + outgoing |wo| (KV projections
            # are shared across groups under GQA and excluded)
            return np.asarray(
                jnp.abs(p["wq"]).sum(axis=(0, 2))
                + jnp.abs(p["wo"]).sum(axis=(1, 2))
            )
        if isinstance(spec, L.MoE):
            # per expert: all of its weight planes + its router column
            return np.asarray(
                jnp.abs(p["wg"]).sum(axis=(1, 2))
                + jnp.abs(p["wu"]).sum(axis=(1, 2))
                + jnp.abs(p["wo"]).sum(axis=(1, 2))
                + jnp.abs(p["router"]).sum(axis=0)
            )
        raise TypeError(f"no weights to score on {type(spec).__name__}")

"""Attribution metrics (pruning criteria).

Six metrics with the uniform API of the reference
(torchpruner/attributions/__init__.py:1-7, README.md:55-90), re-expressed as
jit-compiled functional scorers::

    metric = ShapleyAttributionMetric(model, params, data, loss_fn,
                                      state=state, sv_samples=5)
    scores = metric.run("fc1", find_best_evaluation_layer=True)
"""

from torchpruner_tpu.attributions.base import AttributionMetric
from torchpruner_tpu.attributions.simple import (
    RandomAttributionMetric,
    WeightNormAttributionMetric,
)
from torchpruner_tpu.attributions.activation import (
    APoZAttributionMetric,
    SensitivityAttributionMetric,
    TaylorAttributionMetric,
)
from torchpruner_tpu.attributions.shapley import ShapleyAttributionMetric

__all__ = [
    "AttributionMetric",
    "RandomAttributionMetric",
    "WeightNormAttributionMetric",
    "APoZAttributionMetric",
    "SensitivityAttributionMetric",
    "TaylorAttributionMetric",
    "ShapleyAttributionMetric",
]

"""Attribution metrics (pruning criteria).

Six metrics with the uniform API of the reference
(torchpruner/attributions/__init__.py:1-7, README.md:55-90), re-expressed as
jit-compiled functional scorers::

    metric = ShapleyAttributionMetric(model, params, data, loss_fn,
                                      state=state, sv_samples=5)
    scores = metric.run("fc1", find_best_evaluation_layer=True)

Sweeps that score many metrics/layers over the same data share a
one-pass :class:`ActivationCache` (install on ``metric.capture_cache``;
``layerwise_robustness`` does this automatically): one compiled forward
captures every eval site's activation, and row computation resumes from
the cached ``z`` instead of re-running the prefix per metric × batch.
"""

from torchpruner_tpu.attributions.base import (
    ActivationCache,
    AttributionMetric,
)
from torchpruner_tpu.attributions.simple import (
    RandomAttributionMetric,
    WeightNormAttributionMetric,
)
from torchpruner_tpu.attributions.activation import (
    APoZAttributionMetric,
    SensitivityAttributionMetric,
    TaylorAttributionMetric,
)
from torchpruner_tpu.attributions.shapley import ShapleyAttributionMetric

__all__ = [
    "ActivationCache",
    "AttributionMetric",
    "RandomAttributionMetric",
    "WeightNormAttributionMetric",
    "APoZAttributionMetric",
    "SensitivityAttributionMetric",
    "TaylorAttributionMetric",
    "ShapleyAttributionMetric",
]

"""Forward/backward activation metrics: APoZ, Sensitivity, Taylor.

The reference implements these with forward/backward hooks accumulating
numpy on host per batch (reference apoz.py / sensitivity.py / taylor.py).
Here each is one jit row function; gradients w.r.t. the evaluation-point
activation come from ``jax.grad`` through the model *suffix* only — no
full-model backward, no host round-trips inside the pass.
"""

from __future__ import annotations

import functools

import jax
import numpy as np
import jax.numpy as jnp

from torchpruner_tpu.attributions.base import (
    AttributionMetric,
    needs_taps,
    suffix_loss_fn,
    spatial_sum,
)


def _finish(mode, z, g):
    # row math in f32 even under bf16 scoring: the spatial sum over a
    # feature map accumulates thousands of terms — the 'rows stay f32'
    # guarantee (base.py) starts here, not at the host cast
    z = z.astype(jnp.float32)
    g = g.astype(jnp.float32)
    if mode == "sensitivity":
        # abs first, then spatial sum (reference sensitivity.py:27-30)
        return spatial_sum(jnp.abs(g))
    taylor = spatial_sum(-g * z)  # sum first (reference taylor.py:39-42)
    if mode == "taylor":
        return jnp.abs(taylor)
    return taylor  # taylor_signed


@functools.lru_cache(maxsize=512)
def grad_rows_fn(model, eval_layer, loss_fn, mode: str):
    """jit: (params, state, x, y) -> (batch, n_units) rows for one of
    ``mode in {"apoz", "sensitivity", "taylor", "taylor_signed"}``.

    The gradient is of the *batch-mean* loss, matching the reference's
    ``loss.backward()`` on a mean criterion (reference attributions.py:58-68) —
    per-example grads therefore carry the 1/batch factor, and examples are
    exactly separable because scoring runs in eval mode.

    Top-level non-attention sites split the model at the site and
    differentiate the suffix only.  Nested sites (inside ``Residual``
    bodies) and attention head-context sites instead instrument one full
    forward: activation via ``capture``, gradient as the derivative w.r.t.
    an additive ``perturb`` at zero — same values, computed where
    segmentation cannot cut.
    """
    if needs_taps(model, eval_layer):

        @jax.jit
        def fn(params, state, x, y):
            _, _, z = model.apply(
                params, x, state=state, train=False, capture=eval_layer
            )
            if mode == "apoz":
                return spatial_sum((z > 0).astype(jnp.float32))

            def mean_loss(delta):
                preds, _ = model.apply(
                    params, x, state=state, train=False,
                    perturb=(eval_layer, delta),
                )
                return jnp.mean(loss_fn(preds, y))

            g = jax.grad(mean_loss)(jnp.zeros(z.shape, z.dtype))
            return _finish(mode, z, g)

        return fn

    suffix = suffix_loss_fn(model, eval_layer, loss_fn)

    @jax.jit
    def fn(params, state, x, y):
        z, _ = model.apply(
            params, x, state=state, train=False, to_layer=eval_layer
        )
        if mode == "apoz":
            return spatial_sum((z > 0).astype(jnp.float32))

        def mean_loss(z_):
            return jnp.mean(suffix(params, state, z_, y))

        g = jax.grad(mean_loss)(z)
        return _finish(mode, z, g)

    return fn


class APoZAttributionMetric(AttributionMetric):
    """1−APoZ: per-example count of positive activations per unit (Hu et al.;
    reference apoz.py:15-39). Higher = more alive."""

    def make_row_fn(self, eval_layer, **kw):
        return grad_rows_fn(self.model, eval_layer, self.loss_fn, "apoz")


class SensitivityAttributionMetric(AttributionMetric):
    """Average absolute gradient of the loss w.r.t. each unit's activation
    (Mittal et al.; reference sensitivity.py:13-34)."""

    def make_row_fn(self, eval_layer, **kw):
        return grad_rows_fn(self.model, eval_layer, self.loss_fn, "sensitivity")


class TaylorAttributionMetric(AttributionMetric):
    """First-order Taylor expansion |−g·a| of the loss change on unit removal
    (Molchanov et al.; reference taylor.py:6-49). ``signed=True`` keeps the
    sign (reference taylor.py:44-45)."""

    def __init__(self, *args, signed: bool = False, **kwargs):
        super().__init__(*args, **kwargs)
        self.signed = signed

    def make_row_fn(self, eval_layer, **kw):
        mode = "taylor_signed" if self.signed else "taylor"
        return grad_rows_fn(self.model, eval_layer, self.loss_fn, mode)

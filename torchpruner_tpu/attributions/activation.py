"""Forward/backward activation metrics: APoZ, Sensitivity, Taylor.

The reference implements these with forward/backward hooks accumulating
numpy on host per batch (reference apoz.py / sensitivity.py / taylor.py).
Here each is one jit row function; gradients w.r.t. the evaluation-point
activation come from ``jax.grad`` through the model *suffix* only — no
full-model backward, no host round-trips inside the pass.
"""

from __future__ import annotations

import functools

import jax
import numpy as np
import jax.numpy as jnp

from torchpruner_tpu.attributions.base import (
    AttributionMetric,
    needs_taps,
    suffix_loss_fn,
    spatial_sum,
)


class _GradRowsMetric(AttributionMetric):
    """Shared base of the forward/backward activation metrics: one
    ``mode`` string selects the row math; cached (from-``z``) and
    uncached row fns come from the same pair of compiled cores."""

    mode: str = ""

    def _mode(self) -> str:
        return self.mode

    def make_row_fn(self, eval_layer, **kw):
        return grad_rows_fn(self.model, eval_layer, self.loss_fn,
                            self._mode())

    def make_cached_row_fn(self, eval_layer, **kw):
        if needs_taps(self.model, eval_layer):
            # the capture cache never holds these sites, but guard anyway:
            # the from-z core resumes at a segment boundary only
            return None
        return grad_rows_from_z_fn(self.model, eval_layer, self.loss_fn,
                                   self._mode())

    def cached_row_stream(self, eval_layer, **kw):
        """Gradient modes additionally share ONE memoized suffix gradient
        per (site, loss) across the whole panel (``cache.grads_for``):
        Sensitivity/Taylor/signed-Taylor reduce to elementwise row math
        on the shared ``(z, g)``.  APoZ (no gradient) and every
        miss/fallback case defer to the base implementation."""
        cache = self.capture_cache
        mode = self._mode()
        if (cache is None or mode == "apoz"
                or not cache.matches(self)
                or not cache.has(eval_layer)
                or needs_taps(self.model, eval_layer)):
            return super().cached_row_stream(eval_layer, **kw)
        cache.record_hit(eval_layer)
        finish = finish_rows_fn(mode)
        params = self.cast(self.params)

        def gen():
            grads = cache.grads_for(eval_layer, self.loss_fn, params,
                                    self.state)
            for (z, _y), g in zip(cache.batches_for(eval_layer), grads):
                yield jnp.asarray(finish(z, g), jnp.float32)

        return gen()


def _finish(mode, z, g):
    # row math in f32 even under bf16 scoring: the spatial sum over a
    # feature map accumulates thousands of terms — the 'rows stay f32'
    # guarantee (base.py) starts here, not at the host cast
    z = z.astype(jnp.float32)
    g = g.astype(jnp.float32)
    if mode == "sensitivity":
        # abs first, then spatial sum (reference sensitivity.py:27-30)
        return spatial_sum(jnp.abs(g))
    taylor = spatial_sum(-g * z)  # sum first (reference taylor.py:39-42)
    if mode == "taylor":
        return jnp.abs(taylor)
    return taylor  # taylor_signed


@functools.lru_cache(maxsize=512)
def grad_rows_fn(model, eval_layer, loss_fn, mode: str):
    """jit: (params, state, x, y) -> (batch, n_units) rows for one of
    ``mode in {"apoz", "sensitivity", "taylor", "taylor_signed"}``.

    The gradient is of the *batch-mean* loss, matching the reference's
    ``loss.backward()`` on a mean criterion (reference attributions.py:58-68) —
    per-example grads therefore carry the 1/batch factor, and examples are
    exactly separable because scoring runs in eval mode.

    Top-level non-attention sites split the model at the site and
    differentiate the suffix only.  Nested sites (inside ``Residual``
    bodies) and attention head-context sites instead instrument one full
    forward: activation via ``capture``, gradient as the derivative w.r.t.
    an additive ``perturb`` at zero — same values, computed where
    segmentation cannot cut.
    """
    if needs_taps(model, eval_layer):

        @jax.jit
        def fn(params, state, x, y):
            _, _, z = model.apply(
                params, x, state=state, train=False, capture=eval_layer
            )
            if mode == "apoz":
                return spatial_sum((z > 0).astype(jnp.float32))

            def mean_loss(delta):
                preds, _ = model.apply(
                    params, x, state=state, train=False,
                    perturb=(eval_layer, delta),
                )
                return jnp.mean(loss_fn(preds, y))

            g = jax.grad(mean_loss)(jnp.zeros(z.shape, z.dtype))
            return _finish(mode, z, g)

        return fn

    from_z = grad_rows_from_z_fn(model, eval_layer, loss_fn, mode)

    @jax.jit
    def fn(params, state, x, y):
        z, _ = model.apply(
            params, x, state=state, train=False, to_layer=eval_layer
        )
        return from_z(params, state, z, y)

    return fn


@functools.lru_cache(maxsize=512)
def grad_rows_from_z_fn(model, eval_layer, loss_fn, mode: str):
    """jit: (params, state, z, y) -> (batch, n_units) rows from the
    CAPTURED eval-site activation ``z`` — the prefix-free core of
    :func:`grad_rows_fn` (which computes ``z`` itself and delegates here,
    so cached and uncached rows are the same computation by construction).
    What the one-pass sweep engine dispatches to when the activation
    cache holds the site."""
    suffix = suffix_loss_fn(model, eval_layer, loss_fn)

    @jax.jit
    def fn(params, state, z, y):
        if mode == "apoz":
            return spatial_sum((z > 0).astype(jnp.float32))

        def mean_loss(z_):
            return jnp.mean(suffix(params, state, z_, y))

        g = jax.grad(mean_loss)(z)
        return _finish(mode, z, g)

    return fn


@functools.lru_cache(maxsize=512)
def suffix_grad_fn(model, eval_layer, loss_fn):
    """jit: (params, state, z, y) -> dL/dz of the batch-mean loss through
    the model suffix — the ONE gradient program Sensitivity / Taylor /
    signed-Taylor share on a layer.  The activation cache memoizes its
    per-batch output (``ActivationCache.grads_for``), so a sweep panel
    pays one suffix vjp per batch instead of one per gradient metric,
    and compiles one suffix-vjp executable instead of three."""
    suffix = suffix_loss_fn(model, eval_layer, loss_fn)

    @jax.jit
    def fn(params, state, z, y):
        def mean_loss(z_):
            return jnp.mean(suffix(params, state, z_, y))

        return jax.grad(mean_loss)(z)

    return fn


@functools.lru_cache(maxsize=16)
def finish_rows_fn(mode: str):
    """jit: (z, g) -> rows — the per-mode row math on a shared gradient
    (elementwise + spatial sum; compiles in milliseconds)."""

    @jax.jit
    def fn(z, g):
        return _finish(mode, z, g)

    return fn


class APoZAttributionMetric(_GradRowsMetric):
    """1−APoZ: per-example count of positive activations per unit (Hu et al.;
    reference apoz.py:15-39). Higher = more alive."""

    mode = "apoz"


class SensitivityAttributionMetric(_GradRowsMetric):
    """Average absolute gradient of the loss w.r.t. each unit's activation
    (Mittal et al.; reference sensitivity.py:13-34)."""

    mode = "sensitivity"


class TaylorAttributionMetric(_GradRowsMetric):
    """First-order Taylor expansion |−g·a| of the loss change on unit removal
    (Molchanov et al.; reference taylor.py:6-49). ``signed=True`` keeps the
    sign (reference taylor.py:44-45)."""

    def __init__(self, *args, signed: bool = False, **kwargs):
        super().__init__(*args, **kwargs)
        self.signed = signed

    def _mode(self) -> str:
        return "taylor_signed" if self.signed else "taylor"

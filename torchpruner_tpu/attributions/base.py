"""Attribution metric base — functional replacement for the reference's
hook-driven ``_AttributionMetric`` (reference torchpruner/attributions/
attributions.py).

Where the reference inverts control into torch autograd and stashes
accumulators on module attributes (``_tp_*``), every metric here reduces to a
**row function**: one jit-compiled pure computation
``(params, state, x, y) -> (batch, n_units)`` of per-example scores.  The base
class iterates the dataset, stacks rows on host, and applies the reduction —
and the same row functions are what the distributed scorer shards over the
``data`` mesh axis (torchpruner_tpu/parallel/scoring.py).

Scoring runs the model in eval mode (BatchNorm running statistics), which
keeps examples independent — the property that makes per-example gradients
exact.  Determinism needs no cuDNN toggles (reference attributions.py:108-116):
JAX computations are deterministic and all randomness flows through explicit
PRNG keys.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import numpy as np
import jax.numpy as jnp

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.graph import find_best_evaluation_layer
from torchpruner_tpu.core.segment import SegmentedModel


class AttributionMetric:
    """Base attribution metric.

    Uniform API (reference README.md:55-90)::

        metric = Metric(model, params, data, loss_fn, state=state,
                        reduction="mean")
        scores = metric.run("conv3", find_best_evaluation_layer=True)

    - ``data``: a re-iterable of ``(x, y)`` batches (list/tuple), or a
      zero-arg callable returning an iterator.
    - ``loss_fn(preds, y) -> (batch,)`` per-example losses
      (torchpruner_tpu.utils.losses).
    - ``reduction``: ``"mean" | "sum" | "none"`` or a callable on the
      ``(N, n_units)`` row matrix (reference attributions.py:91-106).
    - ``compute_dtype`` (e.g. ``jnp.bfloat16``): run the scoring forwards
      (and vjps) with params/inputs cast to that dtype — MXU-rate matmuls.
      Loss math and row accumulation stay f32 (utils/losses upcasts), so
      the marginal deltas Shapley chains keep f32 resolution; scores from
      bf16 activations carry bf16-level noise — fine for rankings, opt in
      deliberately for exact-value comparisons.
    """

    #: whether evaluation-point shifting applies (False for weight-only
    #: metrics, reference weight_norm.py:21 / random.py:12).
    shiftable = True

    def __init__(
        self,
        model: SegmentedModel,
        params,
        data,
        loss_fn: Callable,
        *,
        state=None,
        reduction="mean",
        seed: int = 0,
        compute_dtype=None,
    ):
        self.model = model
        self.params = params
        self.state = state if state is not None else {}
        self.data = data
        self.loss_fn = loss_fn
        self.reduction = reduction
        self.seed = seed
        self.compute_dtype = compute_dtype

    # ------------------------------------------------------------------ api

    def run(
        self, layer: str, *, find_best_evaluation_layer: bool = False, **kw
    ) -> np.ndarray:
        """Compute per-unit scores for prunable layer ``layer``."""
        spec = self.model.layer(layer)
        if not isinstance(spec, L.PRUNABLE_TYPES):
            raise TypeError(
                f"attributions require a Dense/Conv layer, got "
                f"{type(spec).__name__} (reference attributions.py:27-32)"
            )
        eval_layer = self.find_evaluation_layer(
            layer, find_best_evaluation_layer
        )
        rows = self.compute_rows(layer, eval_layer, **kw)
        return self.aggregate_over_samples(rows)

    def find_evaluation_layer(self, layer: str, find_best: bool = False) -> str:
        if find_best and self.shiftable:
            return find_best_evaluation_layer(self.model, layer)
        return layer

    def compute_rows(self, layer: str, eval_layer: str, **kw) -> np.ndarray:
        return self._collect(self.make_row_fn(eval_layer, **kw))

    def make_row_fn(self, eval_layer: str, **kw):
        """Return the jit row function ``(params, state, x, y) ->
        (batch, n_units)`` — the unit every data-dependent metric reduces
        to, and what the distributed scorer shards over the data axis
        (torchpruner_tpu/parallel/scoring.py)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement make_row_fn "
            "(weight-only metrics override run() instead)"
        )

    def aggregate_over_samples(self, rows: np.ndarray) -> np.ndarray:
        if self.reduction == "mean":
            return np.mean(rows, 0)
        if self.reduction == "sum":
            return np.sum(rows, 0)
        if self.reduction == "none":
            return rows
        return self.reduction(rows)

    # ------------------------------------------------------------- plumbing

    def batches(self):
        return self.data() if callable(self.data) else iter(self.data)

    def n_units(self, eval_layer: str) -> int:
        # site shape has the unit axis last (== out width everywhere except
        # attention, whose unit is the query head)
        return self.model.site_shape(eval_layer)[-1]

    def cast(self, tree):
        """Apply the metric's ``compute_dtype`` to a pytree's float leaves
        (identity when no compute dtype is set).  Public: the distributed
        scorer applies the SAME cast so local and SPMD rows agree."""
        if self.compute_dtype is None:
            return tree
        from torchpruner_tpu.utils.dtypes import cast_floats

        return cast_floats(tree, self.compute_dtype)

    def run_rows(self, row_fn, params, x, y):
        """One batch of rows under the metric's compute dtype — inputs
        cast, rows coerced to f32 (the single definition of the
        'bf16 forwards, f32 rows' invariant; ``params`` must already be
        ``self.cast``-ed once by the caller)."""
        rows = row_fn(params, self.state, self.cast(jnp.asarray(x)), y)
        return jnp.asarray(rows, jnp.float32)

    def _collect(self, row_fn) -> np.ndarray:
        """Run ``row_fn`` over the dataset, stacking per-example rows
        (always f32 on host, whatever the compute dtype)."""
        params = self.cast(self.params)
        out = []
        for x, y in self.batches():
            out.append(np.asarray(self.run_rows(row_fn, params, x, y)))
        return np.concatenate(out, axis=0)


# ---------------------------------------------------------------------------
# Cached segment computations shared by the data-dependent metrics.  Caching
# on the hashable (model, eval_layer, loss_fn) keeps XLA executables warm
# across passes and invalidates exactly when pruning yields a new spec.
# ---------------------------------------------------------------------------


def needs_taps(model: SegmentedModel, eval_layer: str) -> bool:
    """True when the evaluation site cannot be a segment boundary and metrics
    must instrument a full forward instead: nested sites (inside a
    ``Residual`` body — segment boundaries are top-level) and attention
    layers (whose unit site is the pre-projection head context, not the layer
    output)."""
    if len(L.parse_path(eval_layer)) > 1:
        return True
    return isinstance(model.layer(eval_layer), (L.MultiHeadAttention, L.MoE))


def param_at(params, layer: str):
    """Resolve a (possibly nested, ``"block/child"``) layer's param dict."""
    from torchpruner_tpu.core.plan import _get_path

    return _get_path(params, L.parse_path(layer))


@functools.lru_cache(maxsize=512)
def prefix_fn(model: SegmentedModel, eval_layer: str):
    """jit: (params, state, x) -> activation at ``eval_layer``."""

    @jax.jit
    def fn(params, state, x):
        z, _ = model.apply(params, x, state=state, train=False, to_layer=eval_layer)
        return z

    return fn


@functools.lru_cache(maxsize=512)
def suffix_loss_fn(model: SegmentedModel, eval_layer: str, loss_fn):
    """(params, state, z, y) -> per-example loss (batch,), resuming after
    ``eval_layer`` (the reference's ``run_forward_partial`` with
    ``from_module``, attributions.py:70-89)."""

    def fn(params, state, z, y):
        preds, _ = model.apply(
            params, z, state=state, train=False, from_layer=eval_layer
        )
        return loss_fn(preds, y)

    return fn


def spatial_sum(rows: jnp.ndarray) -> jnp.ndarray:
    """(B, ..., n) -> (B, n): sum every non-batch, non-unit axis."""
    if rows.ndim <= 2:
        return rows
    return rows.sum(axis=tuple(range(1, rows.ndim - 1)))

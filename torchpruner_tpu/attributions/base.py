"""Attribution metric base — functional replacement for the reference's
hook-driven ``_AttributionMetric`` (reference torchpruner/attributions/
attributions.py).

Where the reference inverts control into torch autograd and stashes
accumulators on module attributes (``_tp_*``), every metric here reduces to a
**row function**: one jit-compiled pure computation
``(params, state, x, y) -> (batch, n_units)`` of per-example scores.  The base
class iterates the dataset, stacks rows on host, and applies the reduction —
and the same row functions are what the distributed scorer shards over the
``data`` mesh axis (torchpruner_tpu/parallel/scoring.py).

Scoring runs the model in eval mode (BatchNorm running statistics), which
keeps examples independent — the property that makes per-example gradients
exact.  Determinism needs no cuDNN toggles (reference attributions.py:108-116):
JAX computations are deterministic and all randomness flows through explicit
PRNG keys.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
import jax.numpy as jnp

from torchpruner_tpu import obs
from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.graph import find_best_evaluation_layer
from torchpruner_tpu.core.segment import SegmentedModel, capture_fn


class AttributionMetric:
    """Base attribution metric.

    Uniform API (reference README.md:55-90)::

        metric = Metric(model, params, data, loss_fn, state=state,
                        reduction="mean")
        scores = metric.run("conv3", find_best_evaluation_layer=True)

    - ``data``: a re-iterable of ``(x, y)`` batches (list/tuple), or a
      zero-arg callable returning an iterator.
    - ``loss_fn(preds, y) -> (batch,)`` per-example losses
      (torchpruner_tpu.utils.losses).
    - ``reduction``: ``"mean" | "sum" | "none"`` or a callable on the
      ``(N, n_units)`` row matrix (reference attributions.py:91-106).
    - ``compute_dtype`` (e.g. ``jnp.bfloat16``): run the scoring forwards
      (and vjps) with params/inputs cast to that dtype — MXU-rate matmuls.
      Loss math and row accumulation stay f32 (utils/losses upcasts), so
      the marginal deltas Shapley chains keep f32 resolution; scores from
      bf16 activations carry bf16-level noise — fine for rankings, opt in
      deliberately for exact-value comparisons.
    """

    #: whether evaluation-point shifting applies (False for weight-only
    #: metrics, reference weight_norm.py:21 / random.py:12).
    shiftable = True

    #: whether scoring runs model forwards over the dataset (False for the
    #: weight-only metrics, which override ``run`` and never build a row
    #: fn) — what the capture cache and the distributed scorer key on
    #: instead of reflecting on ``make_row_fn``.
    data_dependent = True

    def __init__(
        self,
        model: SegmentedModel,
        params,
        data,
        loss_fn: Callable,
        *,
        state=None,
        reduction="mean",
        seed: int = 0,
        compute_dtype=None,
    ):
        self.model = model
        self.params = params
        self.state = state if state is not None else {}
        self.data = data
        self.loss_fn = loss_fn
        self.reduction = reduction
        self.seed = seed
        self.compute_dtype = compute_dtype
        #: an :class:`ActivationCache` installed by a sweep driver
        #: (robustness.layerwise_robustness): when it matches this
        #: metric's model/params/data/dtype, row computation starts from
        #: the cached eval-site activation instead of re-running the
        #: prefix forward per batch.
        self.capture_cache: Optional["ActivationCache"] = None

    # ------------------------------------------------------------------ api

    def run(
        self, layer: str, *, find_best_evaluation_layer: bool = False, **kw
    ) -> np.ndarray:
        """Compute per-unit scores for prunable layer ``layer``."""
        spec = self.model.layer(layer)
        if not isinstance(spec, L.PRUNABLE_TYPES):
            raise TypeError(
                f"attributions require a Dense/Conv layer, got "
                f"{type(spec).__name__} (reference attributions.py:27-32)"
            )
        eval_layer = self.find_evaluation_layer(
            layer, find_best_evaluation_layer
        )
        rows = self.compute_rows(layer, eval_layer, **kw)
        scores = self.aggregate_over_samples(rows)
        # provenance: the per-unit score distribution (percentiles, not
        # raw scores) goes to the run ledger, keyed by scoring site —
        # the "by what margin" half of every prune decision's record
        obs.record_scores(eval_layer, scores, layer=layer,
                          method=type(self).__name__, run=self.seed)
        return scores

    def find_evaluation_layer(self, layer: str, find_best: bool = False) -> str:
        if find_best and self.shiftable:
            return find_best_evaluation_layer(self.model, layer)
        return layer

    def compute_rows(self, layer: str, eval_layer: str, **kw) -> np.ndarray:
        stream = self.cached_row_stream(eval_layer, **kw)
        if stream is not None:
            return np.asarray(jnp.concatenate(list(stream), axis=0))
        return self._collect(self.make_row_fn(eval_layer, **kw))

    def make_row_fn(self, eval_layer: str, **kw):
        """Return the jit row function ``(params, state, x, y) ->
        (batch, n_units)`` — the unit every data-dependent metric reduces
        to, and what the distributed scorer shards over the data axis
        (torchpruner_tpu/parallel/scoring.py)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement make_row_fn "
            "(weight-only metrics override run() instead)"
        )

    def make_cached_row_fn(self, eval_layer: str, **kw):
        """Return the jit row function ``(params, state, z, y) ->
        (batch, n_units)`` consuming the CAPTURED activation ``z`` at
        ``eval_layer`` — the prefix-free form of :meth:`make_row_fn` the
        one-pass sweep engine dispatches to.  ``None`` (the default) means
        this metric/site cannot start from a cached activation (weight-only
        metrics; sites that need full-forward instrumentation) and the
        caller falls back to the uncached path."""
        return None

    def cached_row_stream(self, eval_layer: str, **kw):
        """A generator of per-batch f32 row arrays computed from the
        installed capture cache, or ``None`` when the cache is absent,
        mismatched, or cannot serve this metric/site.  Shared by the local
        collector and the distributed scorer (the cache stores activations
        already sharded over the data axis when built with a mesh), and
        the single place hit/miss accounting happens."""
        cache = self.capture_cache
        if cache is None or not self.data_dependent:
            return None
        if not cache.matches(self):
            cache.record_miss(eval_layer)
            return None
        fn = None
        if cache.has(eval_layer):
            fn = self.make_cached_row_fn(eval_layer, **kw)
        if fn is None:
            cache.record_miss(eval_layer)
            return None
        cache.record_hit(eval_layer)

        def gen():
            params = self.cast(self.params)
            for z, y in cache.batches_for(eval_layer):
                yield jnp.asarray(fn(params, self.state, z, y),
                                  jnp.float32)

        return gen()

    def aggregate_over_samples(self, rows: np.ndarray) -> np.ndarray:
        if self.reduction == "mean":
            return np.mean(rows, 0)
        if self.reduction == "sum":
            return np.sum(rows, 0)
        if self.reduction == "none":
            return rows
        return self.reduction(rows)

    # ------------------------------------------------------------- plumbing

    def batches(self):
        return self.data() if callable(self.data) else iter(self.data)

    def n_units(self, eval_layer: str) -> int:
        # site shape has the unit axis last (== out width everywhere except
        # attention, whose unit is the query head)
        return self.model.site_shape(eval_layer)[-1]

    def cast(self, tree):
        """Apply the metric's ``compute_dtype`` to a pytree's float leaves
        (identity when no compute dtype is set).  Public: the distributed
        scorer applies the SAME cast so local and SPMD rows agree."""
        if self.compute_dtype is None:
            return tree
        from torchpruner_tpu.utils.dtypes import cast_floats

        return cast_floats(tree, self.compute_dtype)

    def run_rows(self, row_fn, params, x, y):
        """One batch of rows under the metric's compute dtype — inputs
        cast, rows coerced to f32 (the single definition of the
        'bf16 forwards, f32 rows' invariant; ``params`` must already be
        ``self.cast``-ed once by the caller)."""
        rows = row_fn(params, self.state, self.cast(jnp.asarray(x)), y)
        return jnp.asarray(rows, jnp.float32)

    def _collect(self, row_fn) -> np.ndarray:
        """Run ``row_fn`` over the dataset, stacking per-example rows
        (always f32 on host, whatever the compute dtype).

        Rows stay DEVICE-resident across the loop — each batch's dispatch
        is async, so batch k+1's host-side prep overlaps batch k's device
        compute — and the host pays ONE fetch for the stacked matrix at
        the end instead of a blocking ``np.asarray`` fence per batch
        (the reference's per-batch numpy accumulation, and our old
        behavior, kept the accelerator idle between batches)."""
        params = self.cast(self.params)
        out = []
        for x, y in self.batches():
            out.append(self.run_rows(row_fn, params, x, y))
        if not out:
            raise ValueError(
                f"{type(self).__name__}: empty dataset — no batches to "
                "score")
        return np.asarray(jnp.concatenate(out, axis=0))


# ---------------------------------------------------------------------------
# Cached segment computations shared by the data-dependent metrics.  Caching
# on the hashable (model, eval_layer, loss_fn) keeps XLA executables warm
# across passes and invalidates exactly when pruning yields a new spec.
# ---------------------------------------------------------------------------


def needs_taps(model: SegmentedModel, eval_layer: str) -> bool:
    """True when the evaluation site cannot be a segment boundary and metrics
    must instrument a full forward instead: nested sites (inside a
    ``Residual`` body — segment boundaries are top-level) and attention
    layers (whose unit site is the pre-projection head context, not the layer
    output)."""
    if len(L.parse_path(eval_layer)) > 1:
        return True
    return isinstance(model.layer(eval_layer), (L.MultiHeadAttention, L.MoE))


def param_at(params, layer: str):
    """Resolve a (possibly nested, ``"block/child"``) layer's param dict."""
    from torchpruner_tpu.core.plan import _get_path

    return _get_path(params, L.parse_path(layer))


@functools.lru_cache(maxsize=512)
def prefix_fn(model: SegmentedModel, eval_layer: str):
    """jit: (params, state, x) -> activation at ``eval_layer``."""

    @jax.jit
    def fn(params, state, x):
        z, _ = model.apply(params, x, state=state, train=False, to_layer=eval_layer)
        return z

    return fn


@functools.lru_cache(maxsize=512)
def suffix_loss_fn(model: SegmentedModel, eval_layer: str, loss_fn):
    """(params, state, z, y) -> per-example loss (batch,), resuming after
    ``eval_layer`` (the reference's ``run_forward_partial`` with
    ``from_module``, attributions.py:70-89)."""

    def fn(params, state, z, y):
        preds, _ = model.apply(
            params, z, state=state, train=False, from_layer=eval_layer
        )
        return loss_fn(preds, y)

    return fn


def spatial_sum(rows: jnp.ndarray) -> jnp.ndarray:
    """(B, ..., n) -> (B, n): sum every non-batch, non-unit axis."""
    if rows.ndim <= 2:
        return rows
    return rows.sum(axis=tuple(range(1, rows.ndim - 1)))


# ---------------------------------------------------------------------------
# One-pass sweep capture engine
# ---------------------------------------------------------------------------


class ActivationCache:
    """Cross-layer activation capture shared by a whole scoring sweep.

    The layerwise sweep evaluates every metric × stochastic run × the
    ablation walk at L eval sites; without sharing, each recomputes the
    prefix forward (input → site) per batch — O(L²) prefix layer-forwards
    and L distinct compiled prefix programs across the sweep.  This cache
    runs ONE compiled multi-site program (``core.segment.capture_fn``)
    once per batch, stores each site's activation DEVICE-resident, and
    serves them to every consumer: total prefix work drops to O(L) and
    the prefix executables collapse into one (two with a ragged tail
    batch).

    - ``sites`` is filtered to segment-boundary sites (``needs_taps``
      sites — nested or attention-head — cannot resume a suffix and stay
      on the uncached path, counted as misses).
    - ``compute_dtype`` applies the same float-cast policy the metrics
      use (``bf16 forwards, f32 rows``), so cached and uncached rows
      agree.
    - With ``mesh``, batches are sharded over ``data_axis`` at fill time;
      consumers' row fns then run SPMD on the stored activations with no
      further placement (parallel.scoring.DistributedScorer's path).
    - The fill happens lazily on first use, inside an obs
      ``capture_fill`` span, so CompileWatcher attributes the (single)
      capture compile to it — the CI bound "prefix compiles ≤ 2" reads
      that span.

    Consumers guard with :meth:`matches` (same model/params/data/state/
    dtype objects) — a metric scoring different data or weights falls
    back to computing its own prefix rather than silently reading
    someone else's activations.
    """

    def __init__(self, model: SegmentedModel, params, data, *,
                 sites: Sequence[str], state=None, compute_dtype=None,
                 mesh=None, data_axis: str = "data"):
        self.model = model
        self.params = params          # identity anchor for matches()
        self.state = state if state is not None else {}
        self.data = data
        self.compute_dtype = compute_dtype
        self.mesh = mesh
        self.data_axis = data_axis
        self.sites: Tuple[str, ...] = tuple(dict.fromkeys(
            s for s in sites if not needs_taps(model, s)))
        self.skipped_sites: Tuple[str, ...] = tuple(
            s for s in dict.fromkeys(sites) if needs_taps(model, s))
        #: filled lazily: list of ({site: activation}, y) per batch
        self._batches: Optional[List[Tuple[Dict[str, Any], Any]]] = None
        self._param_aliases: set = set()
        self._state_aliases: set = set()
        #: mesh-placed copies registered by alias_params/alias_state —
        #: _fill reuses them instead of re-replicating from host
        self._params_placed = None
        self._state_placed = None
        #: (site, loss_fn) -> [dL/dz per batch]: the shared per-layer
        #: suffix gradient (see :meth:`grads_for`)
        self._grads: Dict[Tuple[str, Any], List[Any]] = {}
        self.hits = 0
        self.misses = 0
        self.prefix_flops_saved = 0.0
        self._examples = 0
        # per-example prefix-FLOPs estimate per site (computed once; used
        # to price each hit for the obs gauge)
        from torchpruner_tpu.utils.flops import prefix_flops_estimate

        self._site_flops = {
            s: prefix_flops_estimate(model, params, s, batch_size=1)
            for s in self.sites
        }

    # -- guards ------------------------------------------------------------

    def matches(self, metric: AttributionMetric) -> bool:
        """True when ``metric`` scores the exact objects this cache was
        built from (identity, not equality — the cheap check that cannot
        false-positive)."""
        return self.provides_for(
            metric.model, metric.params, metric.state, metric.data,
            metric.compute_dtype,
        )

    def provides_for(self, model, params, state, data,
                     compute_dtype) -> bool:
        return (
            model is self.model
            and self.owns_params(params)
            and self.owns_state(state)
            and data is self.data
            and compute_dtype == self.compute_dtype
        )

    def alias_params(self, params) -> None:
        """Register another pytree holding the SAME parameter values (a
        mesh-replicated copy the sweep made) as valid for consumers'
        identity guards.  The latest alias is also reused by the fill as
        the already-placed tree, skipping a second host→device
        replication."""
        self._param_aliases.add(id(params))
        self._params_placed = params

    def alias_state(self, state) -> None:
        """Same as :meth:`alias_params`, for the state pytree."""
        self._state_aliases.add(id(state))
        self._state_placed = state

    def owns_params(self, params) -> bool:
        return params is self.params or id(params) in self._param_aliases

    def owns_state(self, state) -> bool:
        return (state is self.state
                or (not state and not self.state)
                or id(state) in self._state_aliases)

    def has(self, site: str) -> bool:
        return site in self.sites

    # -- fill / serve ------------------------------------------------------

    def _fill(self):
        if self._batches is not None:
            return
        if not self.sites:
            self._batches = []
            return
        from torchpruner_tpu.utils.dtypes import cast_floats

        fn = capture_fn(self.model, self.sites)
        # prefer the mesh-placed copies a sweep registered via
        # alias_params/alias_state: the cast below then runs on-device on
        # the already-replicated tree instead of paying a second
        # host→device replication of the full model
        params = self._params_placed if self._params_placed is not None \
            else self.params
        state = self._state_placed if self._state_placed is not None \
            else self.state
        if self.compute_dtype is not None:
            params = cast_floats(params, self.compute_dtype)
        put = lambda t: t  # noqa: E731 - identity on a single device
        if self.mesh is not None:
            from torchpruner_tpu.parallel.sharding import (
                batch_sharding,
                replicate,
            )

            if self._params_placed is None:
                params = jax.device_put(params, replicate(self.mesh))
            if state and self._state_placed is None:
                state = jax.device_put(state, replicate(self.mesh))
            bs = batch_sharding(self.mesh, self.data_axis)
            put = lambda t: jax.device_put(t, bs)  # noqa: E731
        # batch prep (asarray / cast / placement) happens OUTSIDE the
        # span so capture_fill's compile bill is the capture program
        # alone — the invariant CI asserts is "capture executables ≤ 2",
        # not "≤ 2 plus a convert per batch shape"
        prepared = []
        n = 0
        for x, y in (self.data() if callable(self.data)
                     else iter(self.data)):
            x = jnp.asarray(x)
            if self.compute_dtype is not None:
                x = cast_floats(x, self.compute_dtype)
            prepared.append((put(x), put(jnp.asarray(y))))
            n += int(np.shape(x)[0])
        filled = []
        with obs.span("capture_fill", sites=len(self.sites)):
            for x, y in prepared:
                filled.append((fn(params, state, x), y))
        self._batches = filled
        self._examples = n

    def batches_for(self, site: str):
        """Yield ``(z, y)`` device arrays per batch for ``site`` (fills
        the cache on first use)."""
        self._fill()
        for caps, y in self._batches:
            yield caps[site], y

    def grads_for(self, site: str, loss_fn, params, state) -> List[Any]:
        """Memoized per-batch suffix gradient dL/dz at ``site`` — the
        SHARED per-layer scoring state: Sensitivity, Taylor and
        signed-Taylor all differentiate the same batch-mean loss through
        the same suffix, so the panel computes (and compiles) that vjp
        once per (site, loss) and each metric keeps only its elementwise
        row math.  ``params`` must already carry the metric's cast (the
        guard in ``matches`` pins every consumer to the same params
        values and compute dtype, so the first caller's cast is
        everyone's cast).  Device-resident, like the activations."""
        key = (site, loss_fn)
        if key not in self._grads:
            from torchpruner_tpu.attributions.activation import (
                suffix_grad_fn,
            )

            gfn = suffix_grad_fn(self.model, site, loss_fn)
            self._grads[key] = [
                gfn(params, state, z, y)
                for z, y in self.batches_for(site)
            ]
        return self._grads[key]

    def drop(self, site: str) -> None:
        """Release ``site``'s device-resident activations and memoized
        gradients.  The sweep calls this once a layer's panel (scoring +
        ablation walk) has finished and no later layer shares the site —
        without it the cache pins O(L × dataset) activation memory for
        the whole sweep instead of O(live sites)."""
        self.sites = tuple(s for s in self.sites if s != site)
        if self._batches is not None:
            for caps, _y in self._batches:
                caps.pop(site, None)
        for key in [k for k in self._grads if k[0] == site]:
            del self._grads[key]

    # -- accounting --------------------------------------------------------
    # hits/misses count SCORING PASSES (one metric run, or one ablation
    # walk) — a unit that does not depend on whether the cache was
    # filled yet, so two identical sweeps always report the same totals.

    def record_hit(self, site: str):
        """One scoring pass served from the cache; prices the avoided
        prefix forwards into the gauge."""
        self._fill()
        self.hits += 1
        saved = self._site_flops.get(site, 0.0) * self._examples
        self.prefix_flops_saved += saved
        obs.record_capture(hits=1, prefix_flops_saved=saved)

    def record_miss(self, site: str):
        """One scoring pass that recomputed its prefix despite this cache
        (unsupported metric/site, or mismatched inputs)."""
        self.misses += 1
        obs.record_capture(misses=1)

    def stats(self) -> Dict[str, float]:
        return {
            "sites": len(self.sites),
            "skipped_sites": len(self.skipped_sites),
            "hits": self.hits,
            "misses": self.misses,
            "prefix_flops_saved": self.prefix_flops_saved,
        }

"""Monte-Carlo Shapley value attribution — the hot loop of the framework.

The reference walks each sampled permutation in Python, re-running the model
suffix once per zeroed unit (``sv_samples × n_units`` forwards per batch,
reference shapley_values.py:28-64) — the dominant cost of its 6.5-hour VGG16
sweep (BASELINE.md).  Here the whole per-batch computation is ONE compiled XLA
program:

- the sequential marginal chain within a permutation (loss deltas chain
  through cumulative masking) is a ``lax.scan`` over units;
- permutations vectorize with ``vmap`` — the MXU sees suffix matmuls batched
  over (permutations × examples);
- the prefix activation is computed once per batch and reused (fast path), or
  a cumulative unit-mask is applied mid-network on a full forward (slow path,
  the functional analog of the reference's masking hook,
  shapley_values.py:92-99).
"""

from __future__ import annotations

import functools

import jax
import numpy as np
import jax.numpy as jnp

from torchpruner_tpu.attributions.base import (
    AttributionMetric,
    needs_taps,
    suffix_loss_fn,
)


@functools.lru_cache(maxsize=512)
def shapley_rows_from_z_fn(model, eval_layer: str, loss_fn):
    """jit: (params, state, z, y, perms) -> (batch, n_units) Shapley rows
    from the CAPTURED eval-site activation ``z`` — the prefix-free core of
    the ``use_partial`` fast path (:func:`shapley_rows_fn` computes ``z``
    itself and delegates here, so cached and uncached rows are the same
    computation by construction).  What the one-pass sweep engine
    dispatches to when the activation cache holds the site."""
    n = model.site_shape(eval_layer)[-1]
    suffix = suffix_loss_fn(model, eval_layer, loss_fn)

    @jax.jit
    def fn(params, state, z, y, perms):
        base = suffix(params, state, z, y)  # (B,) per-example loss
        mask_dt = z.dtype  # matches the activation: a f32 mask would
        # promote a bf16 suffix back to f32 and forfeit the MXU rate

        def masked_loss(mask):
            return suffix(params, state, z * mask, y)

        return _perm_scan(masked_loss, base, perms, n, mask_dt)

    return fn


def _perm_scan(masked_loss, base, perms, n, mask_dt):
    """The sequential marginal chain over sampled permutations shared by
    both Shapley paths: a ``lax.scan`` of cumulative zeroing within a
    permutation, vmapped over permutations."""

    def per_perm(perm):
        def step(carry, u):
            mask, prev = carry
            mask = mask.at[u].set(0.0)  # cumulative zeroing
            loss = masked_loss(mask)
            return (mask, loss), loss - prev

        init = (jnp.ones((n,), mask_dt), base)
        _, deltas = jax.lax.scan(step, init, perm)  # (n, B), perm order
        return jnp.zeros_like(deltas).at[perm].set(deltas)  # unit order

    svs = jax.vmap(per_perm)(perms)  # (S, n, B)
    return jnp.mean(svs, axis=0).T  # (B, n): mean over permutations


@functools.lru_cache(maxsize=512)
def shapley_rows_fn(model, eval_layer: str, loss_fn, use_partial: bool):
    """jit: (params, state, x, y, perms) -> (batch, n_units) Shapley rows.

    ``perms`` is an ``(sv_samples, n_units)`` int array of unit permutations,
    fixed across batches (reference shapley_values.py:45-47).
    """
    n = model.site_shape(eval_layer)[-1]
    from_z = (shapley_rows_from_z_fn(model, eval_layer, loss_fn)
              if use_partial else None)

    @jax.jit
    def fn(params, state, x, y, perms):
        if use_partial:
            z, _ = model.apply(
                params, x, state=state, train=False, to_layer=eval_layer
            )
            return from_z(params, state, z, y, perms)
        else:
            # the mask multiplies the site activation mid-forward; match
            # the dtype the model computes in (first floating param leaf —
            # x may be integer tokens) or a f32 mask would promote a bf16
            # forward back to f32
            from torchpruner_tpu.utils.dtypes import float_dtype_of

            mask_dt = (
                x.dtype
                if jnp.issubdtype(x.dtype, jnp.floating)
                else float_dtype_of(params)
            )

            def masked_loss(mask):
                preds, _ = model.apply(
                    params,
                    x,
                    state=state,
                    train=False,
                    unit_mask=(eval_layer, mask),
                )
                return loss_fn(preds, y)

            base = masked_loss(jnp.ones((n,), mask_dt))
            return _perm_scan(masked_loss, base, perms, n, mask_dt)

    return fn


class ShapleyAttributionMetric(AttributionMetric):
    """Sampled Shapley values of per-unit loss contribution
    (reference shapley_values.py:7-99; cost ``sv_samples × n_units`` suffix
    evaluations per batch, reference README.md:89 — here batched into one XLA
    computation per batch).

    ``use_partial=False`` forces the full-forward masking path (the
    reference's slow path for models without ``forward_partial``); results
    are identical, it only recomputes the prefix under the mask.
    """

    def __init__(self, *args, sv_samples: int = 5, use_partial: bool = True, **kw):
        super().__init__(*args, **kw)
        self.sv_samples = sv_samples
        self.use_partial = use_partial
        self._calls = 0

    def _draw_perms(self, n: int, S: int):
        """Fresh permutations, fixed across batches (reference
        shapley_values.py:45-47) — one draw per scoring request, so the
        cached and uncached paths see the same sequence for a given seed
        and call count."""
        self._calls += 1
        key = jax.random.fold_in(jax.random.PRNGKey(self.seed), self._calls)
        return jax.vmap(lambda k: jax.random.permutation(k, n))(
            jax.random.split(key, S)
        )

    def _resolve(self, eval_layer, sv_samples, use_partial):
        S = sv_samples if sv_samples is not None else self.sv_samples
        partial = use_partial if use_partial is not None else self.use_partial
        if needs_taps(self.model, eval_layer):
            # nested / attention-head sites cannot be segment boundaries —
            # the masking path applies the cumulative unit mask mid-forward
            partial = False
        return S, partial

    def make_row_fn(self, eval_layer: str, sv_samples=None, use_partial=None):
        """Bind drawn permutations and return a plain
        ``(params, state, x, y) -> rows`` function (also used by the
        distributed scorer)."""
        S, partial = self._resolve(eval_layer, sv_samples, use_partial)
        perms = self._draw_perms(self.n_units(eval_layer), S)
        fn = shapley_rows_fn(self.model, eval_layer, self.loss_fn, partial)
        return lambda params, state, x, y: fn(params, state, x, y, perms)

    def make_cached_row_fn(self, eval_layer: str, sv_samples=None,
                           use_partial=None):
        """The prefix-free form: ``(params, state, z, y) -> rows`` from
        the captured eval-site activation.  Only the ``use_partial`` fast
        path can resume from ``z``; the forced masking path (explicit
        ``use_partial=False``, or a site segmentation cannot cut) returns
        ``None`` and scores uncached."""
        S, partial = self._resolve(eval_layer, sv_samples, use_partial)
        if not partial:
            return None
        perms = self._draw_perms(self.n_units(eval_layer), S)
        fn = shapley_rows_from_z_fn(self.model, eval_layer, self.loss_fn)
        return lambda params, state, z, y: fn(params, state, z, y, perms)

"""Structured experiment logging.

Keeps the reference's CSV schema — one row per prune step with pre/post-prune
metrics, parameter count, FLOPs, layer widths and prune time (reference
experiments/utils/utils.py:39-74) — plus JSONL mirroring and proper
``logging`` instead of bare prints (SURVEY.md §5.5).
"""

from __future__ import annotations

import csv
import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Optional

log = logging.getLogger("torchpruner_tpu")


def lint_warning(check: str, message: str, *, default: str = "warning"):
    """One-line runtime diagnostic whose severity follows the static
    analyzer's severity config — the inline twin of a tpu-lint finding.

    Integration points (``shard_params``'s replication fallback) route
    through here so ``analysis.severity_config`` downgrades/CANCELS the
    runtime warning and the batch lint finding with one knob:
    ``"ignore"`` silences, ``"info"`` logs at info level, anything else
    logs at warning level.
    """
    from torchpruner_tpu.analysis.findings import active_severity

    sev = active_severity(check, default)
    if sev == "ignore":
        return
    emit = log.info if sev == "info" else log.warning
    emit("[%s] %s", check, message)

CSV_FIELDS = [
    "timestamp",
    "experiment",
    "step",
    "layer",
    "method",
    "test_loss",
    "test_acc",
    "test_loss_pp",   # post-prune ("pp" naming from reference utils.py:58-62)
    "test_acc_pp",
    "n_params",
    "flops",
    "widths",
    "prune_time",
    "prune_ratio",
    "train_loss",     # from-scratch training rows only (run_train)
    "span_id",        # obs span active when the row was written ("" when
                      # telemetry is off) — joins rows with the events.jsonl
                      # phase stream (obs.current_span_id)
]


@dataclass
class CSVLogger:
    """Append one row per prune step to ``path`` (+ ``path.jsonl``).

    - Appending to an EXISTING csv resumes: ``_step`` continues from the
      last row's step id and the file's own header order is honored (a
      pre-``span_id`` file keeps its narrower schema).
    - File handles are opened once and held (flushed per row), not
      reopened per write; the ``.jsonl`` mirror writes keys in the CSV
      header order so both artifacts agree column-for-column.
    """

    path: str
    experiment: str = "experiment"
    _step: int = 0

    def __post_init__(self):
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._fields = list(CSV_FIELDS)
        header_needed = True
        if os.path.exists(self.path) and os.path.getsize(self.path):
            with open(self.path, newline="") as f:
                reader = csv.reader(f)
                header = next(reader, None)
                if header:
                    self._fields = header
                    header_needed = False
                last = None
                for last in reader:
                    pass
            if last is not None and "step" in self._fields:
                try:
                    self._step = int(last[self._fields.index("step")]) + 1
                except (ValueError, IndexError):
                    pass
        self._csv_f = open(self.path, "a", newline="")
        self._writer = csv.DictWriter(self._csv_f, self._fields,
                                      extrasaction="ignore")
        if header_needed:
            self._writer.writeheader()
        self._jsonl_f = open(self.path + ".jsonl", "a")

    def close(self):
        for f in (getattr(self, "_csv_f", None),
                  getattr(self, "_jsonl_f", None)):
            if f is not None and not f.closed:
                f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def log_prune_step(
        self,
        *,
        layer: str,
        method: str,
        test_loss: float,
        test_acc: float,
        test_loss_pp: float,
        test_acc_pp: float,
        n_params: int,
        flops: Optional[float] = None,
        widths: Optional[dict] = None,
        prune_time: float = 0.0,
        prune_ratio: Optional[float] = None,
    ):
        row = {
            "timestamp": time.strftime("%Y-%m-%d %H:%M:%S"),
            "experiment": self.experiment,
            "step": self._step,
            "layer": layer,
            "method": method,
            "test_loss": f"{test_loss:.6f}",
            "test_acc": f"{test_acc:.6f}",
            "test_loss_pp": f"{test_loss_pp:.6f}",
            "test_acc_pp": f"{test_acc_pp:.6f}",
            "n_params": n_params,
            "flops": flops if flops is not None else "",
            "widths": "-".join(str(v) for v in (widths or {}).values()),
            "prune_time": f"{prune_time:.3f}",
            "prune_ratio": prune_ratio if prune_ratio is not None else "",
        }
        self._write(row)
        log.info(
            "prune step %d [%s/%s]: loss %.4f→%.4f acc %.4f→%.4f params %d",
            self._step, layer, method, test_loss, test_loss_pp,
            test_acc, test_acc_pp, n_params,
        )
        self._step += 1

    def log_epoch(
        self,
        *,
        epoch: int,
        train_loss: float,
        test_loss: float,
        test_acc: float,
        seconds: float = 0.0,
    ):
        """One from-scratch training epoch (run_train): test metrics land in
        their real columns, the training loss in its own."""
        row = {
            "timestamp": time.strftime("%Y-%m-%d %H:%M:%S"),
            "experiment": self.experiment,
            "step": self._step,
            "layer": f"epoch{epoch}",
            "method": "train",
            "test_loss": f"{test_loss:.6f}",
            "test_acc": f"{test_acc:.6f}",
            "test_loss_pp": "",
            "test_acc_pp": "",
            "n_params": "",
            "flops": "",
            "widths": "",
            "prune_time": f"{seconds:.3f}",
            "prune_ratio": "",
            "train_loss": f"{train_loss:.6f}",
        }
        self._write(row)
        log.info(
            "epoch %d: train %.4f test %.4f acc %.4f",
            epoch, train_loss, test_loss, test_acc,
        )
        self._step += 1

    def _write(self, row: dict):
        from torchpruner_tpu import obs

        row.setdefault("span_id", obs.current_span_id() or "")
        self._writer.writerow(row)
        self._csv_f.flush()
        # mirror in the CSV's own column order — consumers diffing the two
        # artifacts see identical key sequences row for row
        ordered = {k: row.get(k, "") for k in self._fields}
        self._jsonl_f.write(json.dumps(ordered) + "\n")
        self._jsonl_f.flush()

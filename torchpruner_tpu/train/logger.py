"""Structured experiment logging.

Keeps the reference's CSV schema — one row per prune step with pre/post-prune
metrics, parameter count, FLOPs, layer widths and prune time (reference
experiments/utils/utils.py:39-74) — plus JSONL mirroring and proper
``logging`` instead of bare prints (SURVEY.md §5.5).
"""

from __future__ import annotations

import csv
import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Optional

log = logging.getLogger("torchpruner_tpu")


def lint_warning(check: str, message: str, *, default: str = "warning"):
    """One-line runtime diagnostic whose severity follows the static
    analyzer's severity config — the inline twin of a tpu-lint finding.

    Integration points (``shard_params``'s replication fallback) route
    through here so ``analysis.severity_config`` downgrades/CANCELS the
    runtime warning and the batch lint finding with one knob:
    ``"ignore"`` silences, ``"info"`` logs at info level, anything else
    logs at warning level.
    """
    from torchpruner_tpu.analysis.findings import active_severity

    sev = active_severity(check, default)
    if sev == "ignore":
        return
    emit = log.info if sev == "info" else log.warning
    emit("[%s] %s", check, message)

CSV_FIELDS = [
    "timestamp",
    "experiment",
    "step",
    "layer",
    "method",
    "test_loss",
    "test_acc",
    "test_loss_pp",   # post-prune ("pp" naming from reference utils.py:58-62)
    "test_acc_pp",
    "n_params",
    "flops",
    "widths",
    "prune_time",
    "prune_ratio",
    "train_loss",     # from-scratch training rows only (run_train)
]


@dataclass
class CSVLogger:
    """Append one row per prune step to ``path`` (+ ``path.jsonl``)."""

    path: str
    experiment: str = "experiment"
    _step: int = 0

    def __post_init__(self):
        new = not os.path.exists(self.path)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        if new:
            with open(self.path, "w", newline="") as f:
                csv.DictWriter(f, CSV_FIELDS).writeheader()

    def log_prune_step(
        self,
        *,
        layer: str,
        method: str,
        test_loss: float,
        test_acc: float,
        test_loss_pp: float,
        test_acc_pp: float,
        n_params: int,
        flops: Optional[float] = None,
        widths: Optional[dict] = None,
        prune_time: float = 0.0,
        prune_ratio: Optional[float] = None,
    ):
        row = {
            "timestamp": time.strftime("%Y-%m-%d %H:%M:%S"),
            "experiment": self.experiment,
            "step": self._step,
            "layer": layer,
            "method": method,
            "test_loss": f"{test_loss:.6f}",
            "test_acc": f"{test_acc:.6f}",
            "test_loss_pp": f"{test_loss_pp:.6f}",
            "test_acc_pp": f"{test_acc_pp:.6f}",
            "n_params": n_params,
            "flops": flops if flops is not None else "",
            "widths": "-".join(str(v) for v in (widths or {}).values()),
            "prune_time": f"{prune_time:.3f}",
            "prune_ratio": prune_ratio if prune_ratio is not None else "",
        }
        self._write(row)
        log.info(
            "prune step %d [%s/%s]: loss %.4f→%.4f acc %.4f→%.4f params %d",
            self._step, layer, method, test_loss, test_loss_pp,
            test_acc, test_acc_pp, n_params,
        )
        self._step += 1

    def log_epoch(
        self,
        *,
        epoch: int,
        train_loss: float,
        test_loss: float,
        test_acc: float,
        seconds: float = 0.0,
    ):
        """One from-scratch training epoch (run_train): test metrics land in
        their real columns, the training loss in its own."""
        row = {
            "timestamp": time.strftime("%Y-%m-%d %H:%M:%S"),
            "experiment": self.experiment,
            "step": self._step,
            "layer": f"epoch{epoch}",
            "method": "train",
            "test_loss": f"{test_loss:.6f}",
            "test_acc": f"{test_acc:.6f}",
            "test_loss_pp": "",
            "test_acc_pp": "",
            "n_params": "",
            "flops": "",
            "widths": "",
            "prune_time": f"{seconds:.3f}",
            "prune_ratio": "",
            "train_loss": f"{train_loss:.6f}",
        }
        self._write(row)
        log.info(
            "epoch %d: train %.4f test %.4f acc %.4f",
            epoch, train_loss, test_loss, test_acc,
        )
        self._step += 1

    def _write(self, row: dict):
        with open(self.path, "a", newline="") as f:
            csv.DictWriter(f, CSV_FIELDS).writerow(row)
        with open(self.path + ".jsonl", "a") as f:
            f.write(json.dumps(row) + "\n")

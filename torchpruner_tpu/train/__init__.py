"""Training subsystem: jitted train/eval steps, the prune→fine-tune driver,
and experiment logging (the TPU-native equivalent of the reference's
experiments/utils/, reference experiments/utils/train.py + utils.py)."""

from torchpruner_tpu.train.loop import (
    Trainer,
    evaluate,
    make_eval_step,
    make_train_step,
    train_epoch,
)
from torchpruner_tpu.train.logger import CSVLogger

__all__ = [
    "Trainer",
    "evaluate",
    "make_eval_step",
    "make_train_step",
    "train_epoch",
    "CSVLogger",
]

"""Jitted training and evaluation loops.

The reference's epoch loops (reference experiments/utils/train.py:11-72) run
batch-at-a-time Python with host-side printing; here the per-batch step is a
single donated jit computation (params/opt-state buffers reused in place —
the XLA equivalent of in-place updates), and the epoch loop only feeds data.

After a prune step changes shapes, build a new ``Trainer`` (or call
``Trainer.rebuild``) — retrace happens automatically because the model spec
changed.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Optional

import jax
import numpy as np
import jax.numpy as jnp
import optax

from torchpruner_tpu import obs
from torchpruner_tpu.core.segment import SegmentedModel
from torchpruner_tpu.resilience import chaos as _chaos
from torchpruner_tpu.utils.losses import accuracy


from torchpruner_tpu.utils.dtypes import cast_floats as _cast_floats


def make_loss_closure(model: SegmentedModel, loss_fn, compute_dtype=None,
                      remat: bool = False, moe_aux_weight: float = 0.0,
                      param_transform: Optional[Callable] = None):
    """``(params, state, x, y, rng) -> (mean loss, new_state)`` — the ONE
    definition of the training forward policy, shared by the local and the
    SPMD train steps.

    ``compute_dtype`` (e.g. ``jnp.bfloat16``) enables mixed precision the
    TPU-native way: master params, optimizer state, mutable state (the
    norm apply rules compute statistics in f32 and cast back — see
    core/layers.py), loss and update math stay float32; the
    forward/backward run with params and inputs cast to ``compute_dtype``
    (MXU-rate matmuls), logits promoted back to f32 before the loss,
    gradients arriving in f32 through the cast's transpose.  ``remat``
    checkpoints composite blocks (recompute-in-backward).
    ``moe_aux_weight`` > 0 adds that multiple of the MoE load-balancing
    loss (Switch-style; collected from every MoE layer, 1.0 when expert
    dispatch is perfectly balanced).

    ``param_transform`` rewrites the params INSIDE the traced step,
    after the compute-dtype cast — the kernel-dispatch hook: e.g.
    ``masking.blocksparse_params`` wraps masked Dense weights in
    :class:`~torchpruner_tpu.ops.blocksparse.BlockSparseWeight` so the
    forward/backward matmuls skip dropped 128-blocks (gradients flow to
    the PLAIN param leaves — the optimizer never sees the wrappers)."""

    def loss(params, state, x, y, rng):
        if compute_dtype is not None:
            params = _cast_floats(params, compute_dtype)
            x = _cast_floats(x, compute_dtype)
        if param_transform is not None:
            params = param_transform(params)
        if moe_aux_weight:
            out, new_state, aux = model.apply(
                params, x, state=state, train=True, rng=rng, remat=remat,
                collect_aux=True,
            )
        else:
            out, new_state = model.apply(
                params, x, state=state, train=True, rng=rng, remat=remat
            )
        if compute_dtype is not None:
            out = out.astype(jnp.float32)
        total = jnp.mean(loss_fn(out, y))
        if moe_aux_weight:
            for a in aux.values():
                total = total + moe_aux_weight * a.astype(jnp.float32)
        return total, new_state

    return loss


def make_train_step(model: SegmentedModel, tx, loss_fn, donate: bool = True,
                    compute_dtype=None, remat: bool = False,
                    accum_steps: int = 1, moe_aux_weight: float = 0.0,
                    grad_norm: bool = False, guard: bool = False,
                    param_transform: Optional[Callable] = None):
    """(params, state, opt_state, x, y, rng) -> (params, state, opt_state,
    loss).  Donation reuses the input buffers for the outputs.  Mixed
    precision / remat per :func:`make_loss_closure`.  ``grad_norm=True``
    makes the loss output a ``(loss, global grad norm)`` pair (opt-in
    telemetry — the extra reduction is fused into the same program).
    ``guard=True`` adds the compiled non-finite guard (see
    :func:`make_step_body`).

    ``accum_steps > 1`` = gradient accumulation: the batch splits into that
    many microbatches, a ``lax.scan`` inside the SAME jit accumulates their
    gradients (peak activation memory shrinks by the factor, one optimizer
    update at the end — how a single chip trains at batch sizes whose
    activations don't fit HBM).  Equal-size microbatches of a mean loss
    make the accumulated gradient identical to the full-batch gradient up
    to float summation order; mutable state (BN statistics) threads through
    the microbatches sequentially."""
    loss_c = make_loss_closure(model, loss_fn, compute_dtype, remat,
                               moe_aux_weight,
                               param_transform=param_transform)
    donate_argnums = (0, 2) if donate else ()
    return jax.jit(make_step_body(loss_c, tx, accum_steps, grad_norm, guard),
                   donate_argnums=donate_argnums)


def make_step_body(loss_c, tx, accum_steps: int = 1,
                   grad_norm: bool = False, guard: bool = False,
                   zero_shardings=None, gather_shardings=None):
    """The un-jitted ``(params, state, opt_state, x, y, rng) -> (params,
    state, opt_state, loss)`` body shared by the local and SPMD trainers —
    callers add their own ``jit`` (with explicit shardings for SPMD).
    With ``grad_norm`` the last output is ``(loss, global grad norm)``.

    ``guard=True`` compiles the non-finite step guard INTO the program:
    ``ok = isfinite(loss) & isfinite(global_norm(grads))`` gates the
    parameter update, the BN-state update, and the opt-state transition
    through ``jnp.where`` — a NaN/Inf step costs its forward/backward but
    leaves the training bundle bit-identical (true skip-and-count, no
    host round-trip in the decision).  The loss output grows a trailing
    ``bad`` flag (0./1.) the host-side ``resilience.StepGuard`` consumes:
    ``(loss, bad)`` / ``(loss, gnorm, bad)`` with ``grad_norm``.

    ``zero_shardings`` (a param-shaped ``NamedSharding`` tree, SPMD
    callers only) compiles ZeRO-style cross-replica weight-update
    sharding into the body: gradients and the params feeding the update
    are pinned to the update domain (param spec + data axis), so XLA
    lowers the gradient reduction as a reduce-scatter and the optax
    update — f32 masters included under ``compute_dtype=bf16`` — runs on
    the local 1/N shard against the data-sharded optimizer state; the
    fresh params are then pinned back to ``gather_shardings`` (the plain
    param placement), which lowers as the all-gather feeding the next
    forward.  The guard's ``jnp.where`` gates in the sharded update
    domain — skip-and-count costs no extra collective."""

    def _finish(l, grads, params, state, opt_state, new_state):
        if zero_shardings is not None:
            # reduce-scatter point: the update's inputs live data-sharded
            grads = jax.lax.with_sharding_constraint(grads, zero_shardings)
            params_u = jax.lax.with_sharding_constraint(
                params, zero_shardings)
        else:
            params_u = params
        updates, new_opt = tx.update(grads, opt_state, params_u)
        new_params = optax.apply_updates(params_u, updates)
        gnorm = optax.global_norm(grads) if (grad_norm or guard) else None
        if guard:
            ok = jnp.isfinite(l) & jnp.isfinite(gnorm)

            def pick(new, old):
                return jax.tree_util.tree_map(
                    lambda a, b: jnp.where(ok, a, b), new, old
                )

            new_params = pick(new_params, params_u)
            new_state = pick(new_state, state)
            new_opt = pick(new_opt, opt_state)
        if zero_shardings is not None and gather_shardings is not None:
            # all-gather point: fresh params return to the param placement
            # for the next forward (also the step's out_sharding)
            new_params = jax.lax.with_sharding_constraint(
                new_params, gather_shardings)
        out = (l,)
        if grad_norm:
            out += (gnorm,)
        if guard:
            out += ((~ok).astype(jnp.float32),)
        return new_params, new_state, new_opt, \
            out if len(out) > 1 else out[0]

    def step(params, state, opt_state, x, y, rng):
        (l, new_state), grads = jax.value_and_grad(
            lambda p: loss_c(p, state, x, y, rng), has_aux=True
        )(params)
        return _finish(l, grads, params, state, opt_state, new_state)

    def step_accum(params, state, opt_state, x, y, rng):
        B = x.shape[0]
        if B % accum_steps:
            raise ValueError(
                f"batch {B} not divisible by accum_steps={accum_steps}"
            )
        m = B // accum_steps
        xs = x.reshape(accum_steps, m, *x.shape[1:])
        ys = y.reshape(accum_steps, m, *y.shape[1:])
        rngs = jax.random.split(rng, accum_steps)
        grad_fn = jax.value_and_grad(loss_c, has_aux=True)

        def body(carry, inp):
            st, gacc, lacc = carry
            xb, yb, r = inp
            (l, new_st), g = grad_fn(params, st, xb, yb, r)
            gacc = jax.tree_util.tree_map(jnp.add, gacc, g)
            return (new_st, gacc, lacc + l), None

        zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
        (new_state, gsum, lsum), _ = jax.lax.scan(
            body, (state, zeros, jnp.float32(0.0)), (xs, ys, rngs)
        )
        grads = jax.tree_util.tree_map(lambda g: g / accum_steps, gsum)
        return _finish(lsum / accum_steps, grads, params, state, opt_state,
                       new_state)

    return step if accum_steps <= 1 else step_accum


def make_multi_step(model: SegmentedModel, tx, loss_fn, donate: bool = True,
                    compute_dtype=None, remat: bool = False,
                    accum_steps: int = 1, moe_aux_weight: float = 0.0):
    """``(params, state, opt_state, xs, ys, rng) -> (params, state,
    opt_state, rng', losses)`` — K FULL optimizer steps inside ONE
    compiled program, scanning over stacked batches ``xs`` of shape
    ``(K, B, ...)``.

    Why: each dispatched program pays a fixed host→device cost; on a
    tunnelled or remote device that cost dwarfs a fast step (measured:
    VGG16's 4.3 ms device step timed at ~27 ms per-dispatch — PERF.md).
    Scanning K steps amortizes the dispatch 1/K, the same trick the
    decode path uses for per-token sampling.  Semantics are EXACTLY K
    sequential :func:`make_train_step` calls: the rng splits once per
    step in the same pattern as ``Trainer.step``, and mutable state
    (BN statistics) threads through the scan carry.
    """
    loss_c = make_loss_closure(model, loss_fn, compute_dtype, remat,
                               moe_aux_weight)
    step = make_step_body(loss_c, tx, accum_steps)

    def multi(params, state, opt_state, xs, ys, rng):
        def body(carry, inp):
            p, st, o, r = carry
            xb, yb = inp
            r, sub = jax.random.split(r)
            p, st, o, l = step(p, st, o, xb, yb, sub)
            return (p, st, o, r), l

        (params, state, opt_state, rng), losses = jax.lax.scan(
            body, (params, state, opt_state, rng), (xs, ys)
        )
        return params, state, opt_state, rng, losses

    donate_argnums = (0, 2) if donate else ()
    return jax.jit(multi, donate_argnums=donate_argnums)


def make_eval_step(model: SegmentedModel, loss_fn):
    """(params, state, x, y) ->
    (sum per-example loss, #correct, n examples, n predictions)."""
    from torchpruner_tpu.utils.losses import prediction_counts

    def step(params, state, x, y):
        out, _ = model.apply(params, x, state=state, train=False)
        losses = loss_fn(out, y)
        correct, n_pred = prediction_counts(out, y)
        return jnp.sum(losses), correct, losses.shape[0], n_pred

    return jax.jit(step)


def make_masked_eval_step(model: SegmentedModel, loss_fn):
    """(params, state, x, y, valid) ->
    (masked loss sum, masked #correct, #valid examples, #predictions).

    ``valid`` is a per-example boolean over the batch dim: padded rows
    (added so a ragged final batch still divides a mesh's data axis)
    contribute nothing to any statistic.  Counts come back as traced
    scalars — unlike :func:`make_eval_step`, where ``n_predictions`` is
    static — because the valid count varies with the mask, not the shape.
    """

    def step(params, state, x, y, valid):
        out, _ = model.apply(params, x, state=state, train=False)
        losses = loss_fn(out, y)
        vf = valid.astype(losses.dtype)
        if out.ndim == y.ndim + 1 and y.ndim >= 2:
            # LM: position t predicts token t+1 (matches prediction_counts)
            pred = jnp.argmax(out[:, :-1], axis=-1)
            correct = jnp.sum((pred == y[:, 1:]) * valid[:, None])
            n_pred = jnp.sum(valid) * (y.shape[1] - 1)
        else:
            correct = jnp.sum((jnp.argmax(out, axis=-1) == y) * valid)
            n_pred = jnp.sum(valid)
        return jnp.sum(losses * vf), correct, jnp.sum(valid), n_pred

    return jax.jit(step)


def _batch_tokens(x, y):
    """Token count of one batch for LM workloads (targets carry a sequence
    dim); ``None`` for classification — keeps ``tokens_per_s`` honest."""
    shape = getattr(y, "shape", ())
    if len(shape) >= 2:
        return int(shape[0]) * int(shape[1])
    return None


def _warn_empty_eval(where: str) -> None:
    """An empty/exhausted evaluation iterator is almost always a caller
    bug (a consumed generator passed where a re-iterable was expected) —
    make it loud: a logger warning plus the ``eval_empty_total`` obs
    counter, so it shows up in telemetry even when logs are swallowed."""
    from torchpruner_tpu.train.logger import log

    obs.inc("eval_empty_total",
            help="evaluate()/train_epoch() calls that saw zero batches")
    log.warning(
        "%s received an empty or exhausted data iterator — no examples "
        "were evaluated (did you pass a one-shot generator instead of a "
        "re-iterable batch list?)", where,
    )


def evaluate(model, params, state, data, loss_fn):
    """Average loss and accuracy over ``data`` (reference train.py:51-72).
    Loss averages per example; accuracy per prediction (== per example for
    classification, per next-token target for LMs)."""
    step = make_eval_step(model, loss_fn)
    tot_l, tot_c, tot_n, tot_p = 0.0, 0, 0, 0
    for x, y in (data() if callable(data) else data):
        l, c, n, n_pred = step(params, state, x, y)
        tot_l += float(l)
        tot_c += int(c)
        tot_n += int(n)
        tot_p += int(n_pred)
    if tot_n == 0:
        _warn_empty_eval("evaluate()")
        raise ValueError("evaluate() got an empty dataset")
    return tot_l / tot_n, tot_c / tot_p


def train_epoch(trainer, data, epoch: int = 0, log_every: int = 20,
                verbose: bool = True):
    """One epoch over ``data``; returns (avg loss, avg acc is not computed
    here — use evaluate).  Mirrors reference train.py:11-48's cadence.
    An empty iterator logs a warning + ``eval_empty_total`` and returns
    ``nan`` (not raised: a final ragged epoch of zero batches should not
    kill a long run, but it must not pass silently either)."""
    t0 = time.perf_counter()
    losses = []
    for i, (x, y) in enumerate(data() if callable(data) else data):
        l = trainer.step(x, y)
        losses.append(float(l))
        if verbose and i % log_every == 0:
            dt = time.perf_counter() - t0
            print(
                f"epoch {epoch} batch {i}: loss {losses[-1]:.4f} "
                f"({dt:.1f}s)", flush=True
            )
    if not losses:
        _warn_empty_eval("train_epoch()")
        return float("nan")
    return float(np.mean(losses))


@dataclass
class Trainer:
    """Holds the mutable training bundle and its compiled step.

    Rebuild after pruning: ``trainer = trainer.rebuild(res.model,
    res.params, res.state, res.opt_state)`` — new spec ⇒ new compiled step
    at the smaller shapes (SURVEY.md §7 "recompilation economics").
    """

    model: SegmentedModel
    params: Any
    state: Any
    tx: Any
    opt_state: Any
    loss_fn: Callable
    rng: Any
    #: None = full f32; jnp.bfloat16 = mixed precision (see make_train_step)
    compute_dtype: Any = None
    #: checkpoint composite blocks (recompute-in-backward; see apply_seq)
    remat: bool = False
    #: >1 = gradient accumulation over scanned microbatches
    accum_steps: int = 1
    #: >0 adds that multiple of the MoE load-balancing loss
    moe_aux_weight: float = 0.0
    #: opt-in telemetry: the compiled step also returns the global grad
    #: norm, recorded via ``obs.record_grad_norm`` (one extra fused
    #: reduction; off by default because fetching it adds a host read)
    grad_norm: bool = False
    #: optional ``resilience.StepGuard``: compiles the non-finite guard
    #: into the step (skip-and-count inside the program) and feeds the
    #: per-step bad flag to the guard — which raises
    #: ``NonFiniteStreakError`` after M consecutive skips.  Reading the
    #: flag fences each step, trading async-dispatch overlap for
    #: fail-fast safety; leave ``None`` on latency-critical paths.
    guard: Any = None
    _step_fn: Any = field(default=None, repr=False)
    _multi_fn: Any = field(default=None, repr=False)
    #: end timestamp of the previous step in the current stepping streak.
    #: Step telemetry records RETURN-TO-RETURN intervals within a streak:
    #: on an async backend the jitted call returns a future in
    #: microseconds and the device time surfaces in the CALLER's fence
    #: (train_epoch's float(loss), run_train's 8-back block) — which lands
    #: between two step calls, so only the interval sees it.  evaluate()
    #: and rebuild() break the streak (their wall time is not step time).
    _t_stream: Any = field(default=None, repr=False)
    step_count: int = 0

    @classmethod
    def create(cls, model, tx, loss_fn, seed: int = 0, params=None,
               state=None, compute_dtype=None, remat: bool = False,
               accum_steps: int = 1, moe_aux_weight: float = 0.0,
               grad_norm: bool = False, guard: Any = None):
        key = jax.random.PRNGKey(seed)
        if params is None:
            params, state = model.init(key)
        return cls(
            model=model,
            params=params,
            state=state if state is not None else {},
            tx=tx,
            opt_state=tx.init(params),
            loss_fn=loss_fn,
            rng=key,
            compute_dtype=compute_dtype,
            remat=remat,
            accum_steps=accum_steps,
            moe_aux_weight=moe_aux_weight,
            grad_norm=grad_norm,
            guard=guard,
        )

    def step(self, x, y) -> float:
        if self._step_fn is None:
            self._step_fn = make_train_step(
                self.model, self.tx, self.loss_fn,
                compute_dtype=self.compute_dtype,
                remat=self.remat,
                accum_steps=self.accum_steps,
                moe_aux_weight=self.moe_aux_weight,
                grad_norm=self.grad_norm,
                guard=self.guard is not None,
            )
        if _chaos.active():
            # deterministic fault injection at the step boundary (kill /
            # synthetic OOM / NaN-poisoned batch) — zero-cost when no
            # chaos config is installed
            _chaos.maybe_kill(self.step_count)
            _chaos.maybe_oom(self.step_count)
            x = _chaos.poison_batch(self.step_count, x)
        self.rng, sub = jax.random.split(self.rng)
        self.params, self.state, self.opt_state, l = self._step_fn(
            self.params, self.state, self.opt_state, x, y, sub
        )
        self.step_count += 1
        if self.grad_norm or self.guard is not None:
            parts = l if isinstance(l, tuple) else (l,)
            l = parts[0]
            if self.grad_norm:
                obs.record_grad_norm(parts[1])
            if self.guard is not None:
                # host read of the compiled guard's flag — may raise
                # NonFiniteStreakError (params already held finite by
                # the in-program skip)
                self.guard.observe(bool(parts[-1]))
        now = time.perf_counter()
        if self._t_stream is not None:
            # a streak's FIRST step is not recorded: on an async backend
            # its within-call time is dispatch-only (µs) and would pollute
            # the histogram floor and inflate derived throughput/MFU
            obs.record_step(now - self._t_stream, x.shape[0],
                            _batch_tokens(x, y))
        self._t_stream = now
        return l

    def multi_step(self, xs, ys):
        """K full optimizer steps in ONE dispatched program over stacked
        batches ``xs`` (K, B, ...) — see :func:`make_multi_step`.
        Returns the (K,) per-step losses; identical results to K
        :meth:`step` calls on the same data."""
        if self._multi_fn is None:
            self._multi_fn = make_multi_step(
                self.model, self.tx, self.loss_fn,
                compute_dtype=self.compute_dtype,
                remat=self.remat,
                accum_steps=self.accum_steps,
                moe_aux_weight=self.moe_aux_weight,
            )
        (self.params, self.state, self.opt_state, self.rng,
         losses) = self._multi_fn(
            self.params, self.state, self.opt_state, xs, ys, self.rng
        )
        k = int(xs.shape[0])
        self.step_count += k
        now = time.perf_counter()
        if self._t_stream is not None:  # see step(): first of a streak
            yshape = getattr(ys, "shape", ())  # (K, B[, S]), no device read
            tok = int(yshape[0] * yshape[1] * yshape[2]) \
                if len(yshape) >= 3 else None
            obs.record_step(now - self._t_stream, int(xs.shape[1]) * k,
                            tok, steps=k)
        self._t_stream = now
        return losses

    def rebuild(self, model, params, state, opt_state) -> "Trainer":
        return Trainer(
            model=model,
            params=params,
            state=state if state is not None else {},
            tx=self.tx,
            opt_state=opt_state,
            loss_fn=self.loss_fn,
            rng=self.rng,
            compute_dtype=self.compute_dtype,
            remat=self.remat,
            accum_steps=self.accum_steps,
            moe_aux_weight=self.moe_aux_weight,
            grad_norm=self.grad_norm,
            guard=self.guard,
            step_count=self.step_count,
        )

    def evaluate(self, data):
        self._t_stream = None  # eval wall time is not step time
        return evaluate(self.model, self.params, self.state, data, self.loss_fn)


def trainer_from_config(cfg, model, tx, loss_fn, *, mesh=None,
                        params=None, state=None, opt_state=None,
                        accum_steps=None, grad_norm=False, guard=None):
    """The ONE trainer factory the experiment drivers share: a
    ``ShardedTrainer`` over ``mesh`` (FSDP/TP placement per
    ``cfg.partition``, ZeRO weight-update sharding per ``cfg.zero``) when
    a mesh is given, else the single-device ``Trainer``.  Restored
    ``params``/``state``/``opt_state`` are ADOPTED at their actual shapes
    — never re-initialized, so pruned/surgered checkpoints (whose trees
    cannot round-trip through ``model.init``) resume on either path.
    ``accum_steps`` overrides ``cfg.accum_steps`` (the resilient runner's
    manifest carries an OOM-doubled value)."""
    cdtype = jnp.bfloat16 if cfg.compute_dtype == "bfloat16" else None
    accum = cfg.accum_steps if accum_steps is None else accum_steps
    if mesh is not None:
        from torchpruner_tpu.parallel import ShardedTrainer

        return ShardedTrainer.create(
            model, tx, loss_fn, mesh, seed=cfg.seed,
            partition=cfg.partition, zero=cfg.zero,
            compute_dtype=cdtype, remat=cfg.remat, accum_steps=accum,
            moe_aux_weight=cfg.moe_aux_weight, grad_norm=grad_norm,
            guard=guard, params=params, state=state, opt_state=opt_state,
        )
    t = Trainer.create(
        model, tx, loss_fn, seed=cfg.seed, params=params, state=state,
        compute_dtype=cdtype, remat=cfg.remat, accum_steps=accum,
        moe_aux_weight=cfg.moe_aux_weight, grad_norm=grad_norm,
        guard=guard,
    )
    if opt_state is not None:
        t.opt_state = opt_state
    return t

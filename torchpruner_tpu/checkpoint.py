"""Shape-aware checkpointing (orbax).

The reference never saves anything (SURVEY.md §5.4) — and pruning makes
checkpointing non-trivial precisely because *shapes change*: a checkpoint
must carry the current architecture widths to be restorable.  A checkpoint
here bundles ``{model spec, params, BN state, optimizer state, prune
history, step}``; restore rebuilds the (pruned) spec first, so arrays load
into the right static shapes.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import shutil
from typing import Any, Dict, Optional

import jax
import numpy as np

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.segment import SegmentedModel


class CheckpointCorruptError(RuntimeError):
    """The checkpoint on disk is incomplete or damaged (truncated write,
    bit rot, torn rename): the content digest recorded at save time does
    not match the bytes present, or a required artifact is missing /
    unparseable.  Restore from an older checkpoint — the atomic save
    protocol guarantees the previously committed one is intact."""

_LAYER_TYPES = {
    cls.__name__: cls
    for cls in (L.Dense, L.Conv, L.BatchNorm, L.LayerNorm, L.RMSNorm,
                L.Activation, L.Pool, L.GlobalPool, L.Flatten, L.Reshape,
                L.Dropout, L.Embedding, L.PosEmbed, L.ClsToken,
                L.MultiHeadAttention, L.GatedDense, L.MoE, L.Residual)
}


def _layer_to_dict(l: L.LayerSpec) -> dict:
    if isinstance(l, L.Residual):
        return {
            "type": "Residual",
            "fields": {
                "name": l.name,
                "body": [_layer_to_dict(c) for c in l.body],
                "shortcut": [_layer_to_dict(c) for c in l.shortcut],
            },
        }
    return {"type": type(l).__name__, "fields": dataclasses.asdict(l)}


def _layer_from_dict(entry: dict) -> L.LayerSpec:
    cls = _LAYER_TYPES[entry["type"]]
    if cls is L.Residual:
        f = entry["fields"]
        return L.Residual(
            f["name"],
            body=tuple(_layer_from_dict(c) for c in f["body"]),
            shortcut=tuple(_layer_from_dict(c) for c in f["shortcut"]),
        )
    fields = {
        k: tuple(v) if isinstance(v, list) else v
        for k, v in entry["fields"].items()
    }
    return cls(**fields)


def spec_to_dict(model: SegmentedModel) -> dict:
    """JSON-serializable model spec (layer kinds + fields + input shape)."""
    return {
        "input_shape": list(model.input_shape),
        "input_dtype": model.input_dtype,
        "layers": [_layer_to_dict(l) for l in model.layers],
    }


def spec_from_dict(d: dict) -> SegmentedModel:
    return SegmentedModel(
        tuple(_layer_from_dict(entry) for entry in d["layers"]),
        tuple(d["input_shape"]),
        d.get("input_dtype", "float32"),
    )


def _pack_qtensors(tree):
    """Replace :class:`QTensor` leaves with plain ``{"q", "scale"}``
    dicts (orbax-serializable) and collect their static aux data keyed
    by path (the same root-relative paths :func:`_unpack_qtensors`
    walks) — quantized serving trees checkpoint losslessly."""
    from torchpruner_tpu.ops.quant import QTensor

    aux: Dict[str, list] = {}

    def walk(t, p):
        if isinstance(t, QTensor):
            aux[p] = [list(t.in_axes), t.bits, t.pack_axis]
            return {"q": t.q, "scale": t.scale}
        if isinstance(t, dict):
            return {k: walk(v, f"{p}/{k}" if p else k)
                    for k, v in t.items()}
        return t

    return walk(tree, ""), aux


def _unpack_qtensors(tree, aux: Dict[str, list]):
    from torchpruner_tpu.ops.quant import QTensor

    def walk(t, p):
        if p in aux:
            in_axes, bits, pack_axis = aux[p]
            return QTensor(t["q"], t["scale"], tuple(in_axes), bits,
                           pack_axis)
        if isinstance(t, dict):
            return {k: walk(v, f"{p}/{k}" if p else k)
                    for k, v in t.items()}
        return t

    return walk(tree, "")


def _np_dtype(name: str) -> np.dtype:
    """Resolve a dtype name including the ml_dtypes extension types
    (bfloat16, int4, float8_*) jax arrays use on TPU."""
    try:
        dt = np.dtype(name)
        if dt.kind != "V":
            return dt
    except TypeError:
        pass
    import ml_dtypes

    return np.dtype(getattr(ml_dtypes, name))


def _write_arrays(path: str, tree: Dict[str, Any]) -> None:
    """Serialize the ``{"params": ..., "state": ..., "opt_state": ...}``
    bundle as ``data.bin`` (concatenated raw leaf buffers) +
    ``index.json`` (tree/path/dtype/shape/offset per leaf).

    Pure numpy on purpose: the orbax/tensorstore writer pulls a second
    native runtime into the training process, and the resilience chaos
    drill caught its allocator corrupting the heap when a run restores a
    checkpoint and compiles from the persistent XLA cache in the same
    process (kill→resume cycles aborted in ``tensorstore`` context
    setup).  Raw bytes + dtype names round-trip every jax dtype
    (bfloat16, int4, float8) exactly, the write path is trivially
    fsync-able, and there is nothing left to deserialize but buffers.

    ``params``/``state`` are nested dicts (walked with sorted keys —
    deterministic byte layout); ``opt_state`` is an arbitrary pytree
    stored as its ``tree_leaves`` sequence (restore rebuilds structure
    from ``tx.init``, exactly as the orbax path always did)."""
    os.makedirs(path, exist_ok=True)
    index = []
    offset = 0

    with open(os.path.join(path, "data.bin"), "wb") as f:

        def emit(tree_name, keypath, leaf):
            nonlocal offset
            # NOT ascontiguousarray: it silently promotes 0-d arrays to
            # shape (1,), and tobytes() already emits C order regardless
            a = np.asarray(jax.device_get(leaf))
            buf = a.tobytes()
            f.write(buf)
            index.append({
                "tree": tree_name, "path": keypath,
                "dtype": str(a.dtype), "shape": list(a.shape),
                "offset": offset, "size": len(buf),
            })
            offset += len(buf)

        def walk(tree_name, t, p):
            if isinstance(t, dict):
                for k in sorted(t):
                    walk(tree_name, t[k], p + [k])
            else:
                emit(tree_name, p, t)

        for name in ("params", "state"):
            if name in tree:
                walk(name, tree[name], [])
        if "opt_state" in tree:
            for i, leaf in enumerate(
                    jax.tree_util.tree_leaves(tree["opt_state"])):
                emit("opt_state", [str(i)], leaf)
        f.flush()
        os.fsync(f.fileno())

    with open(os.path.join(path, "index.json"), "w") as f:
        # "trees" lists what was SAVED, not just what has leaves: a
        # stateless optimizer (plain sgd) has an opt_state with ZERO
        # leaves, and restore must still rebuild it (an absent key would
        # leave the resumed trainer with opt_state=None)
        json.dump({"version": 1, "leaves": index,
                   "trees": sorted(tree.keys())}, f)
        f.flush()
        os.fsync(f.fileno())


def _read_arrays(path: str) -> Dict[str, Any]:
    """Inverse of :func:`_write_arrays` → ``{"params": nested dict,
    "state": nested dict, "opt_state": [leaves...]}`` (keys present only
    when saved)."""
    with open(os.path.join(path, "index.json")) as f:
        index = json.load(f)
    out: Dict[str, Any] = {}
    opt: list = []
    with open(os.path.join(path, "data.bin"), "rb") as f:
        # per-leaf reads into OWNED writable buffers: no whole-file
        # bytes object (peak RAM = arrays, not 2× arrays) and no
        # read-only frombuffer views aliasing shared immutable memory —
        # these leaves feed a DONATING train step
        for e in index["leaves"]:
            f.seek(e["offset"])
            dt = _np_dtype(e["dtype"])
            a = np.empty(
                int(np.prod(e["shape"], dtype=np.int64)), dtype=dt)
            n = f.readinto(memoryview(a.view(np.uint8)))
            if n != e["size"]:
                raise CheckpointCorruptError(
                    f"arrays data.bin truncated: leaf {e['path']} "
                    f"expected {e['size']} bytes, got {n}"
                )
            a = a.reshape(e["shape"])
            if e["tree"] == "opt_state":
                opt.append(a)
                continue
            node = out.setdefault(e["tree"], {})
            for k in e["path"][:-1]:
                node = node.setdefault(k, {})
            node[e["path"][-1] if e["path"] else ""] = a
    for name in index.get("trees", []):
        if name == "opt_state":
            out["opt_state"] = opt  # possibly [] — stateless optimizer
        else:
            out.setdefault(name, {})
    return out


def _tree_digest(root: str) -> str:
    """sha256 over every file under ``root`` in sorted relative-path
    order (path bytes included, so a renamed/missing file changes the
    digest as surely as changed contents)."""
    h = hashlib.sha256()
    root = os.path.abspath(root)
    paths = []
    for d, _dirs, files in os.walk(root):
        for fn in files:
            fp = os.path.join(d, fn)
            paths.append((os.path.relpath(fp, root), fp))
    for rel, fp in sorted(paths):
        h.update(rel.encode())
        h.update(b"\0")
        with open(fp, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        h.update(b"\0")
    return h.hexdigest()


def save_checkpoint(
    path: str,
    model: SegmentedModel,
    params,
    state=None,
    opt_state=None,
    *,
    step: int = 0,
    prune_history: Optional[list] = None,
    extra: Optional[Dict[str, Any]] = None,
):
    """Write a checkpoint directory: ``spec.json`` + orbax array tree.
    Quantized (:class:`~torchpruner_tpu.ops.quant.QTensor`) params are
    supported: the int payload + scale save as arrays and the static
    quantization metadata rides in ``spec.json``.

    The write is ATOMIC and digest-sealed: arrays land in a temp
    directory first, their content digest goes into the metadata, and
    each artifact moves into place via ``os.replace``/``rename`` +
    fsync.  A crash mid-save leaves either the previous complete
    checkpoint or a digest mismatch that :func:`restore_checkpoint`
    reports as :class:`CheckpointCorruptError` — never a silently
    half-written tree restored as if it were whole."""
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    params, q_aux = _pack_qtensors(params)
    meta = {
        "spec": spec_to_dict(model),
        "widths": model.widths(),
        "step": step,
        "prune_history": prune_history or [],
        "extra": extra or {},
    }
    if q_aux:
        meta["quantized"] = q_aux
    if opt_state is not None:
        # the optax pytree structure (node types included) — restore
        # refuses to rebuild under a *different* optimizer whose state
        # happens to flatten to the same leaf count/shapes
        meta["opt_treedef"] = str(jax.tree_util.tree_structure(opt_state))

    tree = {"params": params}
    if state:
        tree["state"] = state
    if opt_state is not None:
        tree["opt_state"] = opt_state

    # 0. sweep TMP litter from ANY earlier pid (a crashed previous
    #    save's half-written trees would otherwise accumulate forever).
    #    .arrays.old.* is deliberately NOT swept here: after a mid-swap
    #    crash it is the only sealed copy of the previous checkpoint,
    #    and deleting it before THIS save reaches its commit point would
    #    make a second crash unrecoverable — old dirs die in step 3.
    for entry in os.listdir(path):
        if entry.startswith(".arrays.tmp."):
            shutil.rmtree(os.path.join(path, entry), ignore_errors=True)

    # 1. arrays → temp dir (raw numpy buffers + index), digest computed
    #    over the real bytes
    tmp_arrays = os.path.join(path, f".arrays.tmp.{os.getpid()}")
    _write_arrays(tmp_arrays, tree)
    meta["digest"] = _tree_digest(tmp_arrays)

    # 2. swap arrays into place (rename is atomic; the displaced old tree
    #    is removed only after the NEW spec.json commits below, so a
    #    crash inside the swap window is recoverable: restore finds the
    #    old tree at .arrays.old.* and verifies it against the old spec)
    final_arrays = os.path.join(path, "arrays")
    old_arrays = os.path.join(path, f".arrays.old.{os.getpid()}")
    if os.path.exists(final_arrays):
        os.rename(final_arrays, old_arrays)
    os.rename(tmp_arrays, final_arrays)

    # 3. spec.json (with the digest) last, atomically (shared helper with
    #    the run manifests): its replace is the commit point — a reader
    #    never sees new-spec/old-arrays.  Only THEN does the displaced
    #    old tree die.
    from torchpruner_tpu.resilience.manifest import atomic_write_json

    atomic_write_json(os.path.join(path, "spec.json"), meta)
    # committed: every displaced tree (this save's and any earlier
    # crashed save's) is now superseded by a consistent arrays+spec pair
    for entry in os.listdir(path):
        if entry.startswith(".arrays.old."):
            shutil.rmtree(os.path.join(path, entry), ignore_errors=True)


def restore_checkpoint(path: str, tx=None, *, check_opt_structure: bool = True):
    """Restore ``(model, params, state, opt_state, meta)``.

    ``opt_state`` needs ``tx`` to rebuild the optax pytree *structure* at the
    pruned shapes (orbax restores raw arrays; structure comes from
    ``tx.init`` on the restored params).  ``check_opt_structure`` compares
    the recorded optimizer treedef against ``tx``'s and refuses a mismatch
    (two optimizers can flatten to identical leaf layouts); pass ``False``
    only when a jax/optax upgrade changed the treedef *repr* of the SAME
    optimizer and the leaf-count/shape checks are trusted instead.

    Integrity: checkpoints written by this module carry a sha256 content
    digest over the array files; a mismatch (truncated write, bit rot,
    torn rename) raises :class:`CheckpointCorruptError` up front instead
    of a deserialization traceback deep inside the array reader.
    Pre-digest checkpoints restore without verification; pre-numpy-format
    (orbax) checkpoints restore through a lazy orbax fallback.
    """
    path = os.path.abspath(path)
    spec_path = os.path.join(path, "spec.json")
    if not os.path.exists(spec_path):
        raise CheckpointCorruptError(
            f"checkpoint {path!r} has no spec.json — the directory is "
            "empty, mid-write, or not a checkpoint"
        )
    try:
        with open(spec_path) as f:
            meta = json.load(f)
    except (json.JSONDecodeError, UnicodeDecodeError, OSError) as e:
        raise CheckpointCorruptError(
            f"checkpoint {path!r} spec.json is unreadable/truncated: "
            f"{type(e).__name__}: {e}"
        ) from e
    arrays_dir = os.path.join(path, "arrays")
    expected = meta.get("digest")
    # hash the tree ONCE (multi-GB checkpoints on the hot resume path)
    actual = _tree_digest(arrays_dir) \
        if expected and os.path.isdir(arrays_dir) else None
    verified = os.path.isdir(arrays_dir) and (not expected
                                              or actual == expected)
    if not verified and expected:
        # crash-window recovery for in-place re-saves: a kill during the
        # arrays swap leaves the PREVIOUS tree (whose digest the current
        # spec.json seals) displaced at .arrays.old.<pid> — verify and
        # swap it back before declaring corruption
        for entry in sorted(os.listdir(path)):
            if not entry.startswith(".arrays.old."):
                continue
            candidate = os.path.join(path, entry)
            if os.path.isdir(candidate) \
                    and _tree_digest(candidate) == expected:
                shutil.rmtree(arrays_dir, ignore_errors=True)
                os.rename(candidate, arrays_dir)
                verified = True
                break
    if not os.path.isdir(arrays_dir):
        raise CheckpointCorruptError(
            f"checkpoint {path!r} has spec.json but no arrays/ tree — "
            "the save was interrupted before its commit point"
        )
    if expected and not verified:
        raise CheckpointCorruptError(
            f"checkpoint {path!r} failed digest verification: "
            f"spec.json sealed sha256 {expected[:16]}… but the array "
            f"files hash to {(actual or '<missing>')[:16]}… — the bytes "
            "on disk were truncated or corrupted after the save "
            "committed"
        )
    model = spec_from_dict(meta["spec"])
    try:
        if os.path.exists(os.path.join(arrays_dir, "index.json")):
            restored = _read_arrays(arrays_dir)
        else:
            # pre-numpy-format checkpoint: orbax read-only fallback
            import orbax.checkpoint as ocp

            restored = ocp.PyTreeCheckpointer().restore(arrays_dir)
    except CheckpointCorruptError:
        raise
    except Exception as e:
        raise CheckpointCorruptError(
            f"checkpoint {path!r} arrays failed to deserialize "
            f"({type(e).__name__}: {str(e)[:200]}) — the tree is "
            "incomplete or damaged"
        ) from e
    params = restored["params"]
    if meta.get("quantized"):
        params = _unpack_qtensors(params, meta["quantized"])
    state = restored.get("state", {})
    opt_state = None
    if tx is not None and "opt_state" in restored:
        template = jax.eval_shape(tx.init, params)
        flat_template, treedef = jax.tree_util.tree_flatten(template)
        flat_restored = jax.tree_util.tree_leaves(restored["opt_state"])
        saved_treedef = meta.get("opt_treedef")
        if (
            check_opt_structure
            and saved_treedef is not None
            and saved_treedef != str(treedef)
        ):
            raise ValueError(
                "optimizer-state structure mismatch: the checkpoint was "
                f"saved with {saved_treedef[:200]}... but tx.init gives "
                f"{str(treedef)[:200]}... — restoring under a different "
                "optimizer would silently cross-wire its slots (pass "
                "check_opt_structure=False if this is the same optimizer "
                "across a jax/optax upgrade)"
            )
        if len(flat_template) != len(flat_restored):
            raise ValueError(
                "optimizer-state layout mismatch: checkpoint has "
                f"{len(flat_restored)} leaves, tx.init gives "
                f"{len(flat_template)}"
            )
        for t, r in zip(flat_template, flat_restored):
            if tuple(t.shape) != tuple(np.shape(r)):
                raise ValueError(
                    f"optimizer-state shape mismatch: {np.shape(r)} vs "
                    f"expected {t.shape}"
                )
        opt_state = jax.tree_util.tree_unflatten(treedef, flat_restored)
    elif "opt_state" in restored:
        opt_state = restored["opt_state"]  # raw nested containers
    return model, params, state, opt_state, meta

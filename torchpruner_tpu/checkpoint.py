"""Shape-aware checkpointing (orbax).

The reference never saves anything (SURVEY.md §5.4) — and pruning makes
checkpointing non-trivial precisely because *shapes change*: a checkpoint
must carry the current architecture widths to be restorable.  A checkpoint
here bundles ``{model spec, params, BN state, optimizer state, prune
history, step}``; restore rebuilds the (pruned) spec first, so arrays load
into the right static shapes.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.segment import SegmentedModel

_LAYER_TYPES = {
    cls.__name__: cls
    for cls in (L.Dense, L.Conv, L.BatchNorm, L.LayerNorm, L.RMSNorm,
                L.Activation, L.Pool, L.GlobalPool, L.Flatten, L.Reshape,
                L.Dropout, L.Embedding, L.PosEmbed, L.ClsToken,
                L.MultiHeadAttention, L.GatedDense, L.MoE, L.Residual)
}


def _layer_to_dict(l: L.LayerSpec) -> dict:
    if isinstance(l, L.Residual):
        return {
            "type": "Residual",
            "fields": {
                "name": l.name,
                "body": [_layer_to_dict(c) for c in l.body],
                "shortcut": [_layer_to_dict(c) for c in l.shortcut],
            },
        }
    return {"type": type(l).__name__, "fields": dataclasses.asdict(l)}


def _layer_from_dict(entry: dict) -> L.LayerSpec:
    cls = _LAYER_TYPES[entry["type"]]
    if cls is L.Residual:
        f = entry["fields"]
        return L.Residual(
            f["name"],
            body=tuple(_layer_from_dict(c) for c in f["body"]),
            shortcut=tuple(_layer_from_dict(c) for c in f["shortcut"]),
        )
    fields = {
        k: tuple(v) if isinstance(v, list) else v
        for k, v in entry["fields"].items()
    }
    return cls(**fields)


def spec_to_dict(model: SegmentedModel) -> dict:
    """JSON-serializable model spec (layer kinds + fields + input shape)."""
    return {
        "input_shape": list(model.input_shape),
        "input_dtype": model.input_dtype,
        "layers": [_layer_to_dict(l) for l in model.layers],
    }


def spec_from_dict(d: dict) -> SegmentedModel:
    return SegmentedModel(
        tuple(_layer_from_dict(entry) for entry in d["layers"]),
        tuple(d["input_shape"]),
        d.get("input_dtype", "float32"),
    )


def _pack_qtensors(tree):
    """Replace :class:`QTensor` leaves with plain ``{"q", "scale"}``
    dicts (orbax-serializable) and collect their static aux data keyed
    by path (the same root-relative paths :func:`_unpack_qtensors`
    walks) — quantized serving trees checkpoint losslessly."""
    from torchpruner_tpu.ops.quant import QTensor

    aux: Dict[str, list] = {}

    def walk(t, p):
        if isinstance(t, QTensor):
            aux[p] = [list(t.in_axes), t.bits, t.pack_axis]
            return {"q": t.q, "scale": t.scale}
        if isinstance(t, dict):
            return {k: walk(v, f"{p}/{k}" if p else k)
                    for k, v in t.items()}
        return t

    return walk(tree, ""), aux


def _unpack_qtensors(tree, aux: Dict[str, list]):
    from torchpruner_tpu.ops.quant import QTensor

    def walk(t, p):
        if p in aux:
            in_axes, bits, pack_axis = aux[p]
            return QTensor(t["q"], t["scale"], tuple(in_axes), bits,
                           pack_axis)
        if isinstance(t, dict):
            return {k: walk(v, f"{p}/{k}" if p else k)
                    for k, v in t.items()}
        return t

    return walk(tree, "")


def save_checkpoint(
    path: str,
    model: SegmentedModel,
    params,
    state=None,
    opt_state=None,
    *,
    step: int = 0,
    prune_history: Optional[list] = None,
    extra: Optional[Dict[str, Any]] = None,
):
    """Write a checkpoint directory: ``spec.json`` + orbax array tree.
    Quantized (:class:`~torchpruner_tpu.ops.quant.QTensor`) params are
    supported: the int payload + scale save as arrays and the static
    quantization metadata rides in ``spec.json``."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    params, q_aux = _pack_qtensors(params)
    meta = {
        "spec": spec_to_dict(model),
        "widths": model.widths(),
        "step": step,
        "prune_history": prune_history or [],
        "extra": extra or {},
    }
    if q_aux:
        meta["quantized"] = q_aux
    if opt_state is not None:
        # the optax pytree structure (node types included) — restore
        # refuses to rebuild under a *different* optimizer whose state
        # happens to flatten to the same leaf count/shapes
        meta["opt_treedef"] = str(jax.tree_util.tree_structure(opt_state))
    with open(os.path.join(path, "spec.json"), "w") as f:
        json.dump(meta, f, indent=2)

    tree = {"params": params}
    if state:
        tree["state"] = state
    if opt_state is not None:
        tree["opt_state"] = opt_state
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(os.path.join(path, "arrays"), tree, force=True)


def restore_checkpoint(path: str, tx=None, *, check_opt_structure: bool = True):
    """Restore ``(model, params, state, opt_state, meta)``.

    ``opt_state`` needs ``tx`` to rebuild the optax pytree *structure* at the
    pruned shapes (orbax restores raw arrays; structure comes from
    ``tx.init`` on the restored params).  ``check_opt_structure`` compares
    the recorded optimizer treedef against ``tx``'s and refuses a mismatch
    (two optimizers can flatten to identical leaf layouts); pass ``False``
    only when a jax/optax upgrade changed the treedef *repr* of the SAME
    optimizer and the leaf-count/shape checks are trusted instead.
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with open(os.path.join(path, "spec.json")) as f:
        meta = json.load(f)
    model = spec_from_dict(meta["spec"])
    ckptr = ocp.PyTreeCheckpointer()
    restored = ckptr.restore(os.path.join(path, "arrays"))
    params = restored["params"]
    if meta.get("quantized"):
        params = _unpack_qtensors(params, meta["quantized"])
    state = restored.get("state", {})
    opt_state = None
    if tx is not None and "opt_state" in restored:
        template = jax.eval_shape(tx.init, params)
        flat_template, treedef = jax.tree_util.tree_flatten(template)
        flat_restored = jax.tree_util.tree_leaves(restored["opt_state"])
        saved_treedef = meta.get("opt_treedef")
        if (
            check_opt_structure
            and saved_treedef is not None
            and saved_treedef != str(treedef)
        ):
            raise ValueError(
                "optimizer-state structure mismatch: the checkpoint was "
                f"saved with {saved_treedef[:200]}... but tx.init gives "
                f"{str(treedef)[:200]}... — restoring under a different "
                "optimizer would silently cross-wire its slots (pass "
                "check_opt_structure=False if this is the same optimizer "
                "across a jax/optax upgrade)"
            )
        if len(flat_template) != len(flat_restored):
            raise ValueError(
                "optimizer-state layout mismatch: checkpoint has "
                f"{len(flat_restored)} leaves, tx.init gives "
                f"{len(flat_template)}"
            )
        for t, r in zip(flat_template, flat_restored):
            if tuple(t.shape) != tuple(np.shape(r)):
                raise ValueError(
                    f"optimizer-state shape mismatch: {np.shape(r)} vs "
                    f"expected {t.shape}"
                )
        opt_state = jax.tree_util.tree_unflatten(treedef, flat_restored)
    elif "opt_state" in restored:
        opt_state = restored["opt_state"]  # raw nested containers
    return model, params, state, opt_state, meta

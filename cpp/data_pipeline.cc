// Native data-pipeline kernels for torchpruner_tpu.
//
// The reference gets host-side batching from torch's C++ DataLoader
// machinery (num_workers, pinned memory); this library is the TPU build's
// native equivalent for the host path: deterministic index shuffling and
// multithreaded batch gather into contiguous buffers that jax.device_put
// can DMA without an extra copy.  Python calls in through ctypes (the GIL
// is released for the duration of each call, so a Python-side prefetch
// thread genuinely overlaps gather with device compute).
//
// Determinism contract: tp_shuffle_indices is splitmix64-seeded
// Fisher-Yates — the pure-Python fallback in data/native.py implements the
// identical sequence, so pipelines are reproducible whether or not the
// native library is present.

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Bumped whenever an exported signature changes; the ctypes loader
// refuses binaries whose version doesn't match (a stale build/ .so bound
// with new argtypes would corrupt memory, not error).
int32_t tp_abi_version() { return 2; }

// splitmix64 (Steele et al.) — tiny, high-quality, trivially portable.
static inline uint64_t splitmix64(uint64_t* s) {
  uint64_t z = (*s += 0x9E3779B97F4A7C15ULL);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

// Fill idx[0..n) with a seeded Fisher-Yates permutation of 0..n-1.
void tp_shuffle_indices(int64_t* idx, int64_t n, uint64_t seed) {
  for (int64_t i = 0; i < n; ++i) idx[i] = i;
  uint64_t s = seed;
  for (int64_t i = n - 1; i > 0; --i) {
    // unbiased bounded draw (rejection sampling)
    uint64_t bound = static_cast<uint64_t>(i) + 1;
    uint64_t threshold = (~bound + 1) % bound;  // 2^64 mod bound
    uint64_t r;
    do {
      r = splitmix64(&s);
    } while (r < threshold);
    uint64_t j = r % bound;
    int64_t t = idx[i];
    idx[i] = idx[j];
    idx[j] = t;
  }
}

// Gather rows: out[b] = src[idx[b]] for b in [0, batch).  row_bytes is the
// byte size of one example; parallelized over a small thread pool for the
// large rows image batches produce.
void tp_gather_rows(const uint8_t* src, const int64_t* idx, int64_t batch,
                    int64_t row_bytes, uint8_t* out, int32_t n_threads) {
  if (n_threads <= 1 || batch < 4 * n_threads) {
    for (int64_t b = 0; b < batch; ++b)
      std::memcpy(out + b * row_bytes, src + idx[b] * row_bytes, row_bytes);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(n_threads);
  int64_t chunk = (batch + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < batch ? lo + chunk : batch;
    if (lo >= hi) break;
    pool.emplace_back([=] {
      for (int64_t b = lo; b < hi; ++b)
        std::memcpy(out + b * row_bytes, src + idx[b] * row_bytes,
                    row_bytes);
    });
  }
  for (auto& th : pool) th.join();
}

// Random horizontal flip + pad-and-crop augmentation on a float32 NHWC
// batch (after the reference's RandomHorizontalFlip + RandomCrop(32,
// padding=4), its cifar10.py:105-110) — fused: the padded intermediate is
// never materialized, out-of-window pixels write the fill value directly.
//
// fill: per-channel border value, c floats, or nullptr for 0.  The kernel
// runs on ALREADY-NORMALIZED data, where the reference pads the RAW image
// with 0 BEFORE Normalize — its border pixels land at -mean/std.  Passing
// fill = -mean/std therefore reproduces the reference's border statistics
// exactly; a nullptr fill (0 = the per-channel mean) is the right value
// for data that was scaled, not standardized (e.g. digits in [0, 1]).
//
// Determinism contract (mirrored bit-for-bit by the Python fallback):
// example i draws from its own splitmix64 stream seeded
// s = seed ^ ((i+1) * 0xD1B54A32D192ED03); draw1 & 1 = flip,
// draw2 % (2*pad+1) = dy, draw3 % (2*pad+1) = dx; the output window at
// (y, x) reads the flipped source at (y + dy - pad, x + dx - pad).
// Per-example streams make the result independent of thread count.
void tp_augment_images(const float* src, int64_t n, int64_t h, int64_t w,
                       int64_t c, int64_t pad, uint64_t seed,
                       const float* fill, float* out, int32_t n_threads) {
  const int64_t span = 2 * pad + 1;
  const int64_t row_elems = w * c;
  const int64_t img_elems = h * row_elems;
  auto fill_row = [=](float* dst, int64_t n_px) {
    if (!fill) {
      std::memset(dst, 0, n_px * c * sizeof(float));
      return;
    }
    for (int64_t p = 0; p < n_px; ++p)
      for (int64_t ch = 0; ch < c; ++ch) dst[p * c + ch] = fill[ch];
  };
  auto one = [=](int64_t i) {
    uint64_t s = seed ^ (0xD1B54A32D192ED03ULL * static_cast<uint64_t>(i + 1));
    const uint64_t flip = splitmix64(&s) & 1ULL;
    const int64_t dy = static_cast<int64_t>(splitmix64(&s) % span);
    const int64_t dx = static_cast<int64_t>(splitmix64(&s) % span);
    const float* im = src + i * img_elems;
    float* ot = out + i * img_elems;
    for (int64_t y = 0; y < h; ++y) {
      float* orow = ot + y * row_elems;
      const int64_t sy = y + dy - pad;
      if (sy < 0 || sy >= h) {
        fill_row(orow, w);
        continue;
      }
      const float* irow = im + sy * row_elems;
      for (int64_t x = 0; x < w; ++x) {
        int64_t sx = x + dx - pad;
        if (sx < 0 || sx >= w) {
          fill_row(orow + x * c, 1);
          continue;
        }
        if (flip) sx = w - 1 - sx;
        std::memcpy(orow + x * c, irow + sx * c, c * sizeof(float));
      }
    }
  };
  if (n_threads <= 1 || n < 2 * n_threads) {
    for (int64_t i = 0; i < n; ++i) one(i);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(n_threads);
  int64_t chunk = (n + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    pool.emplace_back([=] {
      for (int64_t i = lo; i < hi; ++i) one(i);
    });
  }
  for (auto& th : pool) th.join();
}

}  // extern "C"

#!/bin/sh
# On-chip evidence capture — run the moment the axon tunnel answers.
#
# NOTE: on a flaky tunnel prefer the per-leg runner, which survives
# mid-leg wedges, retries across uptime windows, and resumes the
# multi-hour sweep from its checkpoint:
#   python scripts/run_tpu_legs.py --until-complete --watch 8 --aux
# This script is the simple one-shot variant for a HEALTHY tunnel.
#
# Probes first (a hung tunnel must not park the whole capture), then runs
# every measurement the repo's perf story cites, writing committed-quality
# artifacts into results/.  Each step is independently fault-isolated:
# a failure (or a tunnel drop mid-capture) leaves the earlier artifacts.
#
# Usage: sh scripts/capture_tpu.sh   (from the repo root; ~60-90 min warm)
set -u
cd "$(dirname "$0")/.."
mkdir -p results logs

echo "[capture] probing tunnel..."
if ! timeout 75 python -c "import jax; d=jax.devices(); assert d[0].platform=='tpu', d; print(d)"; then
    echo "[capture] tunnel down — aborting (re-run when it answers)"
    exit 1
fi

stamp=$(date -u +%Y-%m-%d_%H%M)
commit=$(git rev-parse --short HEAD)
echo "[capture] tunnel up; commit $commit"

# 1. the full six-leg bench (incl. the non-projected trained sweep,
#    mfu_llama, decode): the headline artifact + refreshed TPU cache.
#    The outer timeout must EXCEED the bench's internal budget (TPU
#    attempt + CPU-reserve wind-down) or the final result line and the
#    bench_tpu_last.json refresh are lost to the external kill.
BENCH_TOTAL_BUDGET_S=10800 timeout 11400 python bench.py \
    > "logs/bench_tpu_${stamp}.jsonl" 2> "logs/bench_tpu_${stamp}.err"
# only a finished on-chip result may be committed under the bench_tpu_
# name; a CPU fallback / boot line / in_progress snapshot is not one
python - "logs/bench_tpu_${stamp}.jsonl" \
    "results/bench_tpu_${stamp}_${commit}.json" <<'EOF' \
    && echo "[capture] bench done (on-chip result committed)" \
    || echo "[capture] bench produced NO finished on-chip result — see logs/"
import json, sys
lines = [l for l in open(sys.argv[1]) if l.strip().startswith("{")]
last = json.loads(lines[-1]) if lines else {}
ok = (last.get("platform") == "tpu" and "stream" not in last
      and "error" not in last and last.get("value") is not None)
if ok:
    open(sys.argv[2], "w").write(lines[-1])
sys.exit(0 if ok else 1)
EOF

# 1b. STAGED ASSERTION (ROADMAP item 2 acceptance): the retuned flash
#     kernel must be >= 1.3x XLA at S >= 8k on the bench leg.  Parse the
#     fresh on-chip bench result's flash row; a miss is loud (nonzero
#     step status in the log) but does not abort the capture — the
#     remaining artifacts are the evidence needed to diagnose it.
python - "results/bench_tpu_${stamp}_${commit}.json" <<'EOF' \
    && echo "[capture] flash >=1.3x @ S>=8k HOLDS" \
    || echo "[capture] flash >=1.3x @ S>=8k FAILED — retune before merging PERF claims"
import json, sys
leg = json.load(open(sys.argv[1])).get("legs", {}).get("flash_attention", {})
sp = leg.get("speedup")
assert sp is not None and "S8192" in str(leg.get("shape", "")), leg
assert sp >= 1.3, f"flash speedup {sp} < 1.3 at {leg.get('shape')} (blocks {leg.get('tuned_blocks')})"
EOF

# 1c. STAGED ASSERTION (FLASH_BWD_XLA_MIN_S retirement): the re-blocked
#     backward (O(block) VMEM, 4D grids) must now COMPILE AND RUN at
#     S=32k — the shape whose whole-sequence VMEM specs made the old
#     backward 500 on remote compile.  Pass = the retirement stands;
#     fail = re-arm via TORCHPRUNER_FLASH_BWD_XLA_MIN_S=32768 and file
#     the Mosaic error.
timeout 1800 python - <<'EOF' \
    && echo "[capture] 32k flash backward compiles+runs — retirement stands" \
    || echo "[capture] 32k flash backward STILL fails — re-arm TORCHPRUNER_FLASH_BWD_XLA_MIN_S=32768"
import jax, jax.numpy as jnp
from torchpruner_tpu.ops.flash_attention import flash_attention
ks = jax.random.split(jax.random.PRNGKey(0), 3)
q, k, v = (jax.random.normal(kk, (1, 32768, 4, 64), jnp.bfloat16) for kk in ks)
g = jax.jit(jax.grad(lambda a, b, c: jnp.sum(
    flash_attention(a, b, c, causal=True).astype(jnp.float32)),
    argnums=(0, 1, 2)))
jax.block_until_ready(g(q, k, v))
print("32k backward ok")
EOF

# 2. flash-attention S-sweep (+ block tuning, persisted into the
#    autotune cache the dispatch path reads): the time-crossover table
timeout 3600 python -m torchpruner_tpu.experiments.flash_sweep --tune \
    --out "results/flash_sweep_tpu_${stamp}_${commit}.json" \
    2> "logs/flash_sweep_${stamp}.err" && echo "[capture] flash sweep done"

# 2b. kernel micro-bench on chip: autotune + parity + kernel_* gauges
#     for the new kernels (decode attention, block-sparse, fused
#     dequant) — the numbers the CPU-smoke gates are placeholders for
timeout 1800 python -m torchpruner_tpu.ops.kernel_bench \
    --obs-dir "logs/kernel_bench_tpu_${stamp}" \
    > "results/kernel_bench_tpu_${stamp}_${commit}.json" \
    2> "logs/kernel_bench_${stamp}.err" \
    && echo "[capture] kernel bench done"

# 2c. int4_bench refresh (PERF.md capture checklist): the decode-matmul
#     bandwidth table, now with the XLA-int8 vs kernel-int8 split that
#     answers the "did the convert fuse" question directly
timeout 1800 python -m torchpruner_tpu.experiments.int4_bench \
    --out "results/int4_bench_tpu_${stamp}_${commit}.json" \
    2> "logs/int4_bench_${stamp}.err" && echo "[capture] int4 bench done"

# 2d. ZeRO weight-update sharding A/B on chip (needs >= 2 devices for a
#     data axis; a 1-chip tunnel window records the skip loudly): the
#     zero-vs-replicated ms/step + planned-opt-bytes rows and the batch
#     sweep one bucket past the r05 MFU plateau, using the freed HBM
timeout 2400 python -m torchpruner_tpu.experiments.zero_bench \
    --out "results/zero_bench_tpu_${stamp}_${commit}.json" \
    2> "logs/zero_bench_${stamp}.err" \
    && echo "[capture] zero bench done" \
    || echo "[capture] zero bench FAILED/skipped (1-chip window? see logs/zero_bench_${stamp}.err)"

# 2e. STAGED ASSERTIONS (ISSUE 9 acceptance): zero-mode planned HBM
#     strictly below replicated at equal batch, and the widened vgg16
#     batch sweep past the r05 MFU plateau (0.25).  A miss is loud but
#     does not abort the capture.
python - "results/zero_bench_tpu_${stamp}_${commit}.json" <<'EOF' \
    && echo "[capture] zero HBM watermark < replicated HOLDS" \
    || echo "[capture] zero HBM assertion FAILED/unavailable — diagnose before merging PERF claims"
import json, sys
z = json.load(open(sys.argv[1]))
for leg in ("vgg", "llama"):
    r = z[leg]
    assert r["opt_bytes"] < r["rep_opt_bytes"], (leg, r)
    data_ax = z["mesh"]["data"]
    assert r["opt_bytes"] <= r["rep_opt_bytes"] / data_ax + (1 << 16), (leg, r)
print("zero opt bytes:", {k: z[k]["opt_ratio"] for k in ("vgg", "llama")})
EOF
python - "results/zero_bench_tpu_${stamp}_${commit}.json" <<'EOF' \
    && echo "[capture] vgg16 batch sweep past MFU 0.25 HOLDS" \
    || echo "[capture] vgg16 zero batch sweep did NOT clear MFU 0.25 — investigate before merging PERF claims"
import json, sys
z = json.load(open(sys.argv[1]))
best = z.get("vgg", {}).get("best_mfu")
assert best is not None and best > 0.25, f"best vgg MFU {best} (sweep: {z.get('vgg', {}).get('batch_sweep')})"
EOF

# 3. compile economics (bucketing x persistent cache) on the real backend
timeout 3600 python -m torchpruner_tpu.experiments.compile_economics \
    --steps 5 --out "results/compile_economics_tpu_${stamp}_${commit}.json" \
    2> "logs/compile_econ_${stamp}.err" && echo "[capture] compile economics done"

# 4. step anatomy: where the milliseconds go, conv-bound vs matmul-bound
timeout 1800 python -m torchpruner_tpu.experiments.step_trace \
    --model vgg16_bn --batch 256 \
    --out "results/steptrace_vgg16_tpu_${stamp}_${commit}.json" \
    2> "logs/steptrace_vgg_${stamp}.err" && echo "[capture] vgg16 trace done"
timeout 1800 python -m torchpruner_tpu.experiments.step_trace \
    --model mfu_llama --batch 32 \
    --out "results/steptrace_mfullama_tpu_${stamp}_${commit}.json" \
    2> "logs/steptrace_llama_${stamp}.err" && echo "[capture] mfu_llama trace done"

# 4b. STAGED ASSERTION (tpu-lint v2 cost model): on-chip the static
#     roofline prediction must land within 30% of the measured step.
#     Runs the smoke train under --obs-dir on the TPU (the driver
#     records predicted_step_ms before the first step), then compares
#     the report's prediction-vs-measured drift row.  Also runs the
#     full collective-contract lint of the llama preset on the real
#     devices with the compile budget raised (CPU skips programs this
#     size; the chip does not).  A miss is loud but does not abort —
#     PERF.md freezes prediction-derived claims until diagnosed.
timeout 1800 python -m torchpruner_tpu --preset llama3_ffn_taylor --smoke \
    --obs-dir "logs/lint_cost_tpu_${stamp}" 2> "logs/lint_cost_${stamp}.err" \
    && python - "logs/lint_cost_tpu_${stamp}" <<'EOF' \
    && echo "[capture] cost-model <30% on-chip HOLDS" \
    || echo "[capture] cost-model >30% drift — recalibrate utils/flops.py peaks before citing predictions"
import sys
from torchpruner_tpu.obs.report import load_run, _scalars_of
sc = _scalars_of(load_run(sys.argv[1]))
drift = sc.get("predicted_vs_measured_step_pct")
assert drift is not None, "no prediction recorded (budget? predict=0?)"
print(f"predicted-vs-measured drift: {drift:+.1f}%")
assert abs(drift) < 30, f"drift {drift:+.1f}% exceeds the 30% target"
EOF
TORCHPRUNER_LINT_COMPILE_BUDGET=1e10 timeout 3600 \
    python -m torchpruner_tpu --lint llama3_ffn_taylor \
    > "results/lint_tpu_${stamp}_${commit}.txt" 2>&1 \
    && echo "[capture] on-chip collective lint clean" \
    || echo "[capture] on-chip collective lint FOUND ERRORS — see results/lint_tpu_${stamp}_${commit}.txt"

# 4c. STAGED ASSERTION (ISSUE 11 acceptance, the vgg16 MFU plateau):
#     `--plan auto` on the vgg16 recipe with measured probes of the
#     top-3 candidates.  The planner's proposed config must beat the
#     0.25 hand-tuned MFU plateau in its MEASURED probe — or the plan
#     artifact must name which roofline term (compute/hbm/ici) says it
#     cannot (an hbm/ici-bound winner is the cost model asserting the
#     plateau is physics, not a bad hand choice).  A miss is loud but
#     does not abort the capture.
timeout 3600 python -m torchpruner_tpu vgg16_digits32_layerwise \
    --plan auto --plan-probe 3 \
    --plan-out "results/plan_vgg16_tpu_${stamp}_${commit}.json" \
    > "results/plan_vgg16_tpu_${stamp}_${commit}.txt" \
    2> "logs/plan_vgg16_${stamp}.err" \
    && python - "results/plan_vgg16_tpu_${stamp}_${commit}.json" <<'EOF' \
    && echo "[capture] planner beats the 0.25 vgg16 MFU plateau (or names the binding term) HOLDS" \
    || echo "[capture] planner vgg16 assertion FAILED — diagnose the plan artifact before merging PERF claims"
import json, sys
plan = json.load(open(sys.argv[1]))
by = {c["label"]: c for c in plan["candidates"]}
assert plan["winner"], f"no feasible candidate: {plan['findings']}"
winner = by[plan["winner"]]
probes = [c for c in plan["candidates"]
          if (c.get("probe") or {}).get("mfu") is not None]
assert probes, "no probe carried an MFU reading"
best = max(probes, key=lambda c: c["probe"]["mfu"])
mfu = best["probe"]["mfu"]
bound = winner["predicted"]["bound"]
print(f"best probed MFU {mfu:.3f} ({best['label']}); "
      f"winner {plan['winner']} is {bound}-bound "
      f"[compute {winner['predicted']['compute_ms']:.3f} / "
      f"hbm {winner['predicted']['hbm_ms']:.3f} / "
      f"ici {winner['predicted']['ici_ms']:.3f} ms]")
if mfu <= 0.25:
    # the plateau stands only if the roofline explains it: the winner
    # must be memory- or wire-bound, not compute-bound (a compute-bound
    # winner under 0.25 MFU means the model is wrong or the config is)
    assert bound in ("hbm", "ici"), (
        f"MFU {mfu:.3f} <= 0.25 but the winner is {bound}-bound — "
        f"the cost model does NOT explain the plateau")
EOF

# 4d. STAGED ASSERTION (ISSUE 12 acceptance, the search campaign):
#     the digits_smoke sparsity-search campaign ON CHIP — the driver
#     runs chip-less (JAX_PLATFORMS=cpu: pricing is static) and each
#     worker gets one TPU core (--trial-devices 1 slices
#     TPU_VISIBLE_DEVICES per slot).  Must hold: the cost-model
#     pre-pricing excludes >=1 candidate BY NAME before anything
#     compiles, and the final frontier carries >=5 measured points,
#     each with checkpoint-digest + ledger provenance.  A miss is loud
#     but does not abort the capture.
JAX_PLATFORMS=cpu timeout 1800 python -m torchpruner_tpu search \
    digits_smoke --jobs 2 --trial-devices 1 \
    --campaign-dir "logs/search_tpu_${stamp}" \
    > "results/search_tpu_${stamp}_${commit}.txt" \
    2> "logs/search_${stamp}.err" \
    && python - "logs/search_tpu_${stamp}" \
        "results/search_tpu_${stamp}_${commit}.txt" <<'EOF' \
    && echo "[capture] on-chip search campaign assertions HOLD" \
    || echo "[capture] on-chip search campaign assertions FAILED — diagnose frontier.json before citing campaign claims"
import json, sys
fr = json.load(open(f"{sys.argv[1]}/frontier.json"))
out = open(sys.argv[2]).read()
excl = fr["excluded"]
assert excl, "pre-pricing excluded nothing"
for e in excl:
    assert f"- `{e['trial_id']}` [{e['excluded_by']}]:" in out, \
        f"exclusion of {e['trial_id']} not printed by name"
pts = [p for p in fr["points"]
       if p["accuracy"] is not None and p["flops"]]
assert len(pts) >= 5, f"only {len(pts)} measured frontier points"
assert all(p["checkpoint_digest"] and p["ledger_run_id"] for p in pts)
print(f"on-chip campaign: {len(pts)} measured points, "
      f"{fr['counts']['early_stopped']} early-stopped, "
      f"excluded by name: {[e['trial_id'] for e in excl]}")
EOF
cp "logs/search_tpu_${stamp}/frontier.json" \
    "results/frontier_tpu_${stamp}_${commit}.json" 2>/dev/null || true

# 5. kernel-level profile leg (obs.profile): continuous capture windows
#    over a short mfu_llama train run — the on-chip per-kernel table +
#    roofline positions ROADMAP item 2's retune reads, plus a fresh
#    kernel-scalar report to gate future captures against
timeout 1800 python -m torchpruner_tpu --preset llama3_ffn_taylor --smoke \
    --obs-dir "logs/profile_tpu_${stamp}" --profile-every 20 \
    --profile-steps 4 2> "logs/profile_${stamp}.err" \
    && python -m torchpruner_tpu obs profile "logs/profile_tpu_${stamp}" \
        > "results/kernel_profile_tpu_${stamp}_${commit}.md" \
    && cp "logs/profile_tpu_${stamp}/profile.json" \
        "results/kernel_profile_tpu_${stamp}_${commit}.json" \
    && echo "[capture] kernel profile leg done"

echo "[capture] done — review results/, update PERF.md, commit"

#!/bin/bash
# probe every 3 min until the deadline; on tunnel-up capture the int4
# microbench artifact, then refresh the decode leg (int4 rows)
cd /root/repo
deadline=$(( $(date +%s) + ${1:-14000} ))
while [ $(date +%s) -lt $deadline ]; do
  if timeout 70 python -c "import jax; d=jax.devices()[0]; assert d.platform=='tpu'" 2>/dev/null; then
    echo "[watch] tunnel up at $(date -u +%H:%M)"
    timeout 1800 python -m torchpruner_tpu.experiments.int4_bench \
      --out results/int4_bench_tpu_$(date -u +%Y-%m-%d_%H%M)_$(git rev-parse --short HEAD).json \
      && echo "[watch] int4 bench captured"
    timeout 2400 python -u scripts/run_tpu_legs.py --legs llama_decode \
      && echo "[watch] decode leg refreshed"
    exit 0
  fi
  echo "[watch] down at $(date -u +%H:%M)"
  sleep 180
done
echo "[watch] window over"
exit 2

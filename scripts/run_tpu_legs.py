"""Per-leg on-chip bench capture with hard timeouts and a tunnel watcher.

Why this exists: ``bench.py``'s orchestrator runs all six legs inside ONE
child process.  When the axon tunnel dies MID-LEG, the in-flight RPC never
returns — the child can't be interrupted from inside (the hang is in
device code, not Python), so one wedged leg burns the whole budget
(round-4 postmortem: ``vgg16_train`` sat 33 min at 0 CPU with the tunnel
dead under it; the round-3 run produced nothing the same way).

This runner gives each leg its OWN process and a hard kill timeout,
probes the tunnel between legs (a dead tunnel skips the rest instead of
wedging), and merges every finished leg into ``bench_tpu_last.json``
(via :func:`bench._write_tpu_cache`'s carry-forward semantics) plus a
``results/``-quality artifact — so evidence lands leg by leg, not
all-or-nothing.

Usage::

    python scripts/run_tpu_legs.py                  # capture now (probe first)
    python scripts/run_tpu_legs.py --watch 8        # probe every 2 min for
                                                    # up to 8 h, capture when
                                                    # the tunnel answers
    python scripts/run_tpu_legs.py --legs mfu_llama,llama_decode
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402  (the leg functions + cache merge live there)

#: capture order: cheap, high-information legs first so a tunnel drop
#: mid-capture keeps the most evidence per minute; hard per-leg kill
#: timeouts sized ~4x the round-2 cold-run observations.
LEGS = [
    ("mnist_prune", 600),
    ("plan", 1800),
    ("mfu_llama", 2400),
    ("vgg16_train", 2400),
    ("flash_attention", 1800),
    ("llama_decode", 1800),
    ("vgg16_robustness", 14400),
]

_CHILD_SRC = r"""
import json, sys
sys.path.insert(0, {repo!r})
import bench
from torchpruner_tpu.utils.compilation_cache import enable_persistent_cache
enable_persistent_cache()
import inspect
fn = getattr(bench, "_leg_" + {fn_suffix!r})
kw = {{}}
if "progress" in inspect.signature(fn).parameters:
    def _progress(partial):
        print("LEGPART " + json.dumps(partial), flush=True)
    kw["progress"] = _progress
print("LEGJSON " + json.dumps(fn(False, **kw)), flush=True)
"""

#: leg name -> the bench module's function suffix
_FN = {
    "mnist_prune": "mnist",
    "plan": "plan",
    "vgg16_robustness": "vgg_robustness",
    "vgg16_train": "vgg_train",
    "mfu_llama": "mfu_llama",
    "flash_attention": "flash_attention",
    "llama_decode": "llama_decode",
}


def probe(timeout_s: float = 75) -> str | None:
    """Device kind if the tunnel answers within ``timeout_s``, else None."""
    try:
        p = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices()[0]; "
             "assert d.platform == 'tpu', d; "
             "print(getattr(d, 'device_kind', 'tpu'))"],
            capture_output=True, text=True, timeout=timeout_s,
        )
        return p.stdout.strip() if p.returncode == 0 else None
    except subprocess.TimeoutExpired:
        return None


def run_leg(name: str, timeout_s: float) -> dict:
    """One leg in its own process; returns the leg dict (an ``error``
    entry on kill/crash, with the last checkpointed partial and a stderr
    tail for the postmortem)."""
    import threading
    from collections import deque

    src = _CHILD_SRC.format(repo=REPO, fn_suffix=_FN[name])
    t0 = time.time()
    proc = subprocess.Popen([sys.executable, "-u", "-c", src],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    final, partial = None, None
    killed = False

    def _kill():
        nonlocal killed
        killed = True
        proc.kill()

    timer = threading.Timer(timeout_s, _kill)
    timer.start()
    err_tail: deque = deque(maxlen=8)

    def _pump_stderr():
        for line in proc.stderr:
            err_tail.append(line[:400])

    pump = threading.Thread(target=_pump_stderr, daemon=True)
    pump.start()
    try:
        for line in proc.stdout:
            # a line truncated by the hard kill must not crash the
            # capture loop — the whole point is salvaging earlier legs
            try:
                if line.startswith("LEGJSON "):
                    final = json.loads(line[8:])
                    # result in hand: don't wait out a child that wedges
                    # during teardown over a dead tunnel
                    break
                elif line.startswith("LEGPART "):
                    partial = json.loads(line[8:])
            except json.JSONDecodeError:
                pass
    finally:
        timer.cancel()
    if proc.poll() is None:
        proc.kill()
    rc = proc.wait()
    pump.join(timeout=5)
    if final is not None:
        return final
    err = {"error": (f"leg killed after {timeout_s:.0f}s (tunnel wedge?)"
                     if killed else f"leg child died rc={rc}"),
           "elapsed_s": round(time.time() - t0, 1),
           "stderr_tail": "".join(err_tail)[-1200:]}
    if isinstance(partial, dict):  # keep checkpointed layers from a kill
        err = {**partial, **err}
        err.pop("in_progress", None)
    return err


def capture(leg_names, device_kind: str, just_probed: bool = False) -> dict:
    stamp = time.strftime("%Y-%m-%d_%H%M", time.gmtime())
    commit = bench._git_commit()
    out_path = os.path.join(
        REPO, "results", f"bench_tpu_{stamp}_{commit}.json")
    legs: dict = {}
    for i, (name, timeout_s) in enumerate(leg_names):
        # the caller's successful probe covers the first leg — don't pay
        # (or flakily fail) a second back-to-back probe round trip
        if not (i == 0 and just_probed) and probe() is None:
            legs[name] = {"skipped": "tunnel down at leg start"}
            print(f"[legs] {name}: tunnel down, skipping", flush=True)
            continue
        print(f"[legs] {name} starting (timeout {timeout_s}s)", flush=True)
        t0 = time.time()
        legs[name] = run_leg(name, timeout_s)
        status = "error" if "error" in legs[name] else "ok"
        print(f"[legs] {name} {status} in {time.time() - t0:.0f}s",
              flush=True)
        # merge + persist after EVERY leg: a later wedge keeps earlier
        # wins, and the headline assembles from current + carried legs so
        # a subset capture never nulls out a previously-captured headline
        merged = bench._merge_cached_legs(legs)
        # the leg children enable the persistent cache (_CHILD_SRC); record
        # the same dir here so the artifact doesn't claim cache-less runs
        from torchpruner_tpu.utils.compilation_cache import ENV_VAR, _DEFAULT
        cache_dir = os.environ.get(ENV_VAR) or _DEFAULT
        result = bench._assemble(merged, "tpu", device_kind, cache_dir, False)
        result["capture"] = "per-leg (scripts/run_tpu_legs.py)"
        bench._write_tpu_cache(result)
        with open(out_path, "w") as f:
            json.dump({
                "measured_at": time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                "git_commit": commit,
                "device_kind": device_kind,
                "legs_this_run": sorted(legs),
                "result": result,
            }, f, indent=1)
    return legs


#: auxiliary captures after the legs (the rest of scripts/capture_tpu.sh,
#: PERF.md's evidence beyond bench numbers): (tag, timeout_s, argv-maker).
#: Each runs in its own subprocess with a hard timeout, like the legs.
AUX = [
    ("int4_bench", 1800, lambda out:
        [sys.executable, "-u", "-m",
         "torchpruner_tpu.experiments.int4_bench", "--out", out]),
    ("llama8b_decode", 5400, lambda out:
        [sys.executable, "-u", "-m",
         "torchpruner_tpu.experiments.llama8b_decode", "--out", out]),
    ("flash_sweep", 3600, lambda out:
        [sys.executable, "-u", "-m",
         "torchpruner_tpu.experiments.flash_sweep", "--tune", "--out", out]),
    ("sweep_scaling", 3600, lambda out:
        [sys.executable, "-u", "-m",
         "torchpruner_tpu.experiments.sweep_scaling", "--out", out]),
    ("compile_economics", 3600, lambda out:
        [sys.executable, "-u", "-m",
         "torchpruner_tpu.experiments.compile_economics", "--steps", "5",
         "--out", out]),
    ("steptrace_vgg16", 1800, lambda out:
        [sys.executable, "-u", "-m",
         "torchpruner_tpu.experiments.step_trace", "--model", "vgg16_bn",
         "--batch", "256", "--out", out]),
    ("steptrace_mfullama", 1800, lambda out:
        [sys.executable, "-u", "-m",
         "torchpruner_tpu.experiments.step_trace", "--model", "mfu_llama",
         "--batch", "32", "--out", out]),
]


def run_aux(device_kind: str, tags=None) -> dict:
    """The non-bench captures, tunnel-probed and fault-isolated per item;
    artifacts land in results/ named {tag}_tpu_{stamp}_{commit}.json,
    stderr in logs/aux_{tag}_{stamp}.err for postmortems.  Returns the
    unfinished tags mapped to why — ``"down"`` (tunnel skip: retry freely)
    or ``"failed"`` (real attempt died: counts against the attempt cap).
    ``tags=None`` runs all of ``AUX``."""
    stamp = time.strftime("%Y-%m-%d_%H%M", time.gmtime())
    commit = bench._git_commit()
    failed: dict = {}
    print(f"[legs] aux captures on {device_kind}", flush=True)
    for tag, timeout_s, mk in AUX:
        if tags is not None and tag not in tags:
            continue
        if probe() is None:
            print(f"[legs] aux {tag}: tunnel down, skipping", flush=True)
            failed[tag] = "down"
            continue
        out = os.path.join(REPO, "results",
                           f"{tag}_tpu_{stamp}_{commit}.json")
        err_path = os.path.join(REPO, "logs", f"aux_{tag}_{stamp}.err")
        print(f"[legs] aux {tag} starting (timeout {timeout_s}s)",
              flush=True)
        t0 = time.time()
        with open(err_path, "w") as err_f:
            try:
                rc = subprocess.run(mk(out), timeout=timeout_s,
                                    stdout=subprocess.DEVNULL,
                                    stderr=err_f, cwd=REPO).returncode
            except subprocess.TimeoutExpired:
                rc = -1
        ok = rc == 0 and os.path.exists(out)
        if not ok:
            failed[tag] = "failed"
        print(f"[legs] aux {tag} {'ok' if ok else f'rc={rc}'} in "
              f"{time.time() - t0:.0f}s"
              + ("" if ok else f" (stderr: {err_path})"), flush=True)
    return failed


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--legs", default=None,
                    help="comma-separated subset (default: all six)")
    ap.add_argument("--watch", type=float, default=0, metavar="HOURS",
                    help="probe every --interval until the tunnel answers, "
                         "for up to HOURS; 0 = probe once and exit if down")
    ap.add_argument("--interval", type=float, default=120)
    ap.add_argument("--aux", action="store_true",
                    help="after the legs, also capture flash sweep / "
                         "compile economics / step traces into results/")
    ap.add_argument("--until-complete", action="store_true",
                    help="keep watching + recapturing across tunnel "
                         "windows until every requested leg (and aux "
                         "item) has captured ok or the --watch window "
                         "ends; short legs first, then aux, then the "
                         "resumable multi-hour sweep")
    ap.add_argument("--max-attempts", type=int, default=3,
                    help="per-leg attempt cap in --until-complete mode "
                         "(a persistently wedging leg must not starve "
                         "the rest; the sweep leg is exempt — it "
                         "resumes from its checkpoint)")
    args = ap.parse_args(argv)
    if args.legs:
        known = {n for n, _ in LEGS}
        requested = args.legs.split(",")
        bad = [n for n in requested if n not in known]
        if bad:  # fail FAST — not after an hours-long watch window
            ap.error(f"unknown legs {bad}; choose from {sorted(known)}")
        wanted = [(n, t) for n, t in LEGS if n in set(requested)]
    else:
        wanted = LEGS
    deadline = time.time() + args.watch * 3600
    if args.until_complete:
        return run_until_complete(wanted, deadline, args)
    while True:
        kind = probe()
        if kind:
            print(f"[legs] tunnel up ({kind})", flush=True)
            legs = capture(wanted, kind, just_probed=True)
            ok = sum(1 for v in legs.values()
                     if "error" not in v and "skipped" not in v)
            print(f"[legs] done: {ok}/{len(wanted)} legs ok", flush=True)
            aux_failed = run_aux(kind) if args.aux else {}
            return 0 if ok and not aux_failed else 1
        if time.time() >= deadline:
            print("[legs] tunnel down, watch window over", flush=True)
            return 2
        print("[legs] tunnel down, waiting...", flush=True)
        time.sleep(args.interval)


def run_until_complete(wanted, deadline, args) -> int:
    """Loop watch→capture across tunnel windows until everything has
    landed (or the window ends): short legs first (highest evidence per
    tunnel minute), aux artifacts second, the cross-window-resumable
    robustness sweep last.  A leg that errors ``--max-attempts`` times is
    dropped with a notice so one wedger can't starve the rest."""
    short = {n: t for n, t in wanted if n != "vgg16_robustness"}
    sweep = {n: t for n, t in wanted if n == "vgg16_robustness"}
    aux_left = ([t for t, _, _ in AUX] if args.aux else [])
    attempts: dict = {}
    aux_passes = 0
    gave_up: list = []

    def capture_phase(pool, kind) -> None:
        legs = capture([(n, pool[n]) for n in pool], kind,
                       just_probed=True)
        for n, v in legs.items():
            if "error" not in v and "skipped" not in v:
                pool.pop(n, None)
            elif "error" in v:
                attempts[n] = attempts.get(n, 0) + 1
                if n not in sweep and attempts[n] >= args.max_attempts:
                    print(f"[legs] {n}: giving up after "
                          f"{attempts[n]} attempts", flush=True)
                    pool.pop(n, None)
                    gave_up.append(n)

    while True:
        if not (short or aux_left or sweep):
            if gave_up:
                print(f"[legs] until-complete: done, but gave up on "
                      f"{gave_up}", flush=True)
                return 1
            print("[legs] until-complete: everything captured", flush=True)
            return 0
        kind = probe()
        if kind is None:
            if time.time() >= deadline:
                left = sorted(short) + aux_left + sorted(sweep)
                print(f"[legs] watch window over; uncaptured: {left}",
                      flush=True)
                return 2
            time.sleep(args.interval)
            continue
        print(f"[legs] tunnel up ({kind})", flush=True)
        if short:
            capture_phase(short, kind)
        elif aux_left:
            outcome = run_aux(kind, aux_left)
            aux_left = sorted(outcome)
            # tunnel-down skips retry freely; only real failed attempts
            # count against the cap
            if any(why == "failed" for why in outcome.values()):
                aux_passes += 1
            if aux_left and aux_passes >= args.max_attempts:
                print(f"[legs] aux: giving up on {aux_left} after "
                      f"{aux_passes} failed passes", flush=True)
                gave_up.extend(aux_left)
                aux_left = []
            elif aux_left:
                time.sleep(min(args.interval, 60))
        elif sweep:
            capture_phase(sweep, kind)
        if time.time() >= deadline and (short or aux_left or sweep):
            left = sorted(short) + aux_left + sorted(sweep)
            print(f"[legs] watch window over; uncaptured: {left}",
                  flush=True)
            return 2


if __name__ == "__main__":
    sys.exit(main())

"""Benchmark: the reference's headline workloads on TPU.

Six legs (baselines from BASELINE.md where the reference has one):

1. ``mnist_prune`` — the "Pruning Untrained Networks" MNIST experiment end
   to end (28 s on the reference's CUDA GPU): untrained 784-2024-2024-10 FC
   net, Shapley attribution (sv_samples=5, bf16 forwards) on 1000
   validation examples for both hidden layers (outermost first), pruning
   all negative-attribution units — including all JIT compilation and the
   shape-changing recompile between the two prune steps.
2. ``vgg16_robustness`` — the north-star 6.5 h layerwise-robustness sweep
   (every prunable layer × the 8-method panel, 3 runs for stochastic
   methods), measured END TO END with no projection, on a VGG16-bn
   trained in-leg on digits32 (real sklearn digit scans at CIFAR-10
   geometry) so the AUC table is meaningful.  The panel's ablation walks
   run as ONE vmapped ``lax.scan`` per batch in bf16
   (experiments/robustness.py) instead of the reference's per-unit
   Python forwards.
3. ``vgg16_train`` — steady-state VGG16-bn training-step time, img/s per
   chip, and MFU (achieved FLOPs / peak) via XLA cost analysis; bf16
   mixed precision with the f32 step alongside.
4. ``flash_attention`` — Pallas flash fwd+bwd kernels vs the XLA einsum
   path: grad-step time and compiled temp memory at S=2048 (the O(S·Dh)
   vs O(S²) backward-memory claim, measured).
5. ``llama_decode`` — KV-cache decode throughput (tokens/s) through
   ``generate``, dense vs after a 25% FFN-channel structured prune
   (example 04's serving flow; no reference baseline — the reference has
   no inference loop).  On TPU the model is the ~200M ``mfu_llama``
   (decode reads every param per token: an HBM-bound, serving-shaped
   number); the CPU fallback keeps the CPU-sized ``llama_tiny``.
6. ``mfu_llama`` — train-step MFU on a ~200M-param Llama whose FLOPs are
   large MXU-shaped matmuls: the machinery's MFU ceiling, next to the
   conv-bound VGG16 number.
7. ``blocksparse`` — the block-sparse matmul (ops/blocksparse.py) at 50%
   structured sparsity vs the same-machinery dense matmul AND a full
   Dense-MLP train step masked-dense vs kernel-dispatched: the ms/step
   the pruned structure actually buys (not just the FLOPs gauge).
8. ``zero`` — ZeRO-style cross-replica weight-update sharding A/B
   (``ShardedTrainer(zero=True)`` vs replicated updates) on the
   vgg16/llama train shapes: ms/step, planned optimizer bytes/chip both
   ways (the 1/data-axis drop, asserted), and on TPU the batch sweep one
   bucket past the r05 MFU plateau using the freed HBM
   (experiments/zero_bench.py; ``zero_*`` gauges ride obs diff).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}
(vs_baseline > 1 means faster than the reference.)  On TPU the headline is
the projected sweep wall-clock vs the 6.5 h baseline; on the CPU fallback
only the MNIST leg runs (the VGG legs are TPU-sized) and it is the headline.

Robustness contract (round-1 postmortem: BENCH_r01.json was a raw
traceback; round-3 postmortem: BENCH_r03.json was ``parsed: null`` because
the driver killed the run before any JSON line was printed): the default
invocation is an *orchestrator* that

1. prints a parseable null-skeleton JSON line (with the cached last TPU
   measurement attached) IMMEDIATELY, before doing anything that can hang;
2. caps the TPU preflight at a fixed share of the budget (2 probes by
   default, ~3 min worst case);
3. runs the measurement in a child process whose stdout is streamed line
   by line — the child prints a full result snapshot after EVERY leg, and
   the orchestrator forwards each one as its own stdout line, so a driver
   kill at ANY moment leaves the finished legs parseable (the LAST JSON
   line on stdout is always the best available result);
4. falls back to a CPU measurement (clearly labelled) when the TPU probe
   or attempt fails, skipping legs that cannot fit the remaining
   ``BENCH_TOTAL_BUDGET_S`` budget.

``--run`` executes one measurement in-process (what the orchestrator
spawns).
"""

from __future__ import annotations

import inspect
import json
import os
import subprocess
import sys
import tempfile
import time

#: last successful TPU measurement, refreshed by the orchestrator on every
#: TPU run — attached (clearly labelled) to CPU-fallback output so a
#: transient tunnel outage at measurement time doesn't erase the recorded
#: TPU evidence.
TPU_CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "bench_tpu_last.json")

#: partial results, rewritten by the measurement child after EVERY leg —
#: if the child is killed mid-run (orchestrator or driver timeout), the
#: legs that did finish are salvaged from here instead of being lost.
PARTIAL_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "bench_partial_last.json")

#: the multi-hour sweep's cross-window resume scratch: trained weights +
#: finished layers, so the sweep accumulates across tunnel uptime windows
#: shorter than itself (gitignored; deleted when a sweep completes).
ROBUSTNESS_RESUME = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "logs",
    "vgg_robustness_resume.pkl")

#: total wall-clock budget for the WHOLE orchestration (preflight +
#: attempts).  The round-2 driver accepted an ~11 min run; the round-3
#: driver killed the run somewhere past ~23 min — so the default (20 min)
#: keeps the worst case (capped preflight + CPU-fallback legs) under the
#: observed kill threshold with margin.  Manual deep runs (full TPU
#: sweep) should raise this, e.g. ``BENCH_TOTAL_BUDGET_S=10800``.
TOTAL_BUDGET_S = float(os.environ.get("BENCH_TOTAL_BUDGET_S", "1200"))

#: wall-clock reserved for the CPU fallback attempt while a TPU attempt
#: runs: a TPU child that hangs mid-leg is killed early enough for the
#: fallback's headline (MNIST, ~520 s on the 1-core host) to finish.
CPU_RESERVE_S = float(os.environ.get("BENCH_CPU_RESERVE_S", "600"))

#: coarse cold-run upper estimates per leg, (tpu_s, cpu_s) — used with the
#: budget deadline to SKIP legs that cannot finish instead of getting
#: killed mid-leg with nothing to show.  TPU numbers from the round-2 run
#: (cold compiles through the tunnel); CPU numbers from the round-2/3
#: fallback runs on the 1-core host.
_LEG_EST_S = {
    # TPU numbers re-based on the round-4 captures, warm persistent
    # cache (observed: mnist 60 s, vgg_train 32 s, mfu_llama 51 s,
    # decode 63 s, flash 10 s, sweep 928 s), with 2-6x cold margin
    "mnist_prune": (150, 520),
    "resilience": (150, 240),
    "plan": (240, 120),
    "search": (180, 180),
    "zero": (300, 420),
    "vgg16_train": (120, 3600),
    "mfu_llama": (180, 3600),
    "llama_decode": (180, 300),
    "serve": (240, 300),
    "serve_prefix": (180, 240),
    "fleet": (180, 180),
    "flash_attention": (60, 600),
    "blocksparse": (90, 300),
    "vgg16_robustness": (1500, 100000),
}

#: committed obs reports live here (obs_report_*<platform>*.json): a
#: bench run with BENCH_OBS_DIR auto-diffs its fresh report against the
#: newest matching one (torchpruner_tpu.obs.report) and attaches the
#: outcome to the result — the regression check nobody has to eyeball.
RESULTS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "results")

#: default gates for the bench auto-diff (informational: violations are
#: REPORTED in the result record, never fail the bench).  Timing gates
#: are generous — bench hosts vary; the CI smoke applies its own file.
BENCH_GATES = {
    "step_time_mean_s": {"max_increase_pct": 75},
    "mfu": {"max_decrease_pct": 25},
    "compile_s": {"max_increase_pct": 200},
    "missing_rounds": {"max": 0},
    "round_post_acc": {"max_decrease": 0.1},
}

MNIST_BASELINE_S = 28.0  # reference MNIST FC prune wall-clock (BASELINE.md)
SWEEP_BASELINE_S = 6.5 * 3600.0  # reference 15-layer × 8-method sweep
SWEEP_PANEL_RUNS = 14  # 5 deterministic + 3 stochastic × 3 runs per layer
SWEEP_N_LAYERS = 15


def _peak_flops(device) -> float | None:
    from torchpruner_tpu.utils.flops import peak_bf16_flops

    return peak_bf16_flops(device)


def _kernel_window(row: dict, steps: int = 1,
                   flops_per_step: float | None = None):
    """One profiler capture window around a leg's already-measured
    workload: the top-5 per-kernel rows (obs.profile.OneShotCapture)
    land in ``row["kernels"]`` — op-level evidence next to every
    headline number (the flash 0.983x and int4 staleness questions are
    exactly "which kernel", ROADMAP item 2).  Runs AFTER the timed
    section so trace overhead never pollutes the timing; failures
    degrade to no row, never a leg error."""
    from torchpruner_tpu.obs.profile import OneShotCapture

    return OneShotCapture(row, steps=steps, flops_per_step=flops_per_step)


def _leg_mnist(smoke: bool) -> dict:
    """Leg 1: untrained-MNIST Shapley prune, timed end to end."""
    import jax

    from torchpruner_tpu.attributions import ShapleyAttributionMetric
    from torchpruner_tpu.utils.profiling import hard_fence
    from torchpruner_tpu.core.graph import pruning_graph
    from torchpruner_tpu.core.pruner import prune_by_scores
    from torchpruner_tpu.core.segment import init_model
    from torchpruner_tpu.data import load_dataset
    from torchpruner_tpu.models import mnist_fc
    from torchpruner_tpu.utils.flops import param_count
    from torchpruner_tpu.utils.losses import cross_entropy_loss

    if smoke:
        from torchpruner_tpu.models.mlp import fc_net

        model = fc_net(784, hidden=(64, 64))
        n_examples, bs = 64, 32
    else:
        model = mnist_fc()
        n_examples, bs = 1000, 500
    params, state = init_model(model, seed=0)
    val = load_dataset("mnist_flat", "val", n=n_examples, seed=0)
    batches = val.batches(bs)
    # stage data on device once (input pipeline, not the measured prune loop)
    batches = [(jax.numpy.asarray(x), jax.numpy.asarray(y)) for x, y in batches]
    hard_fence(batches)

    params_before = param_count(params)
    t0 = time.perf_counter()
    targets = [g.target for g in pruning_graph(model)][::-1]  # fc2 then fc1
    for target in targets:
        # scoring forwards in bf16 (MXU rate); loss deltas accumulate f32
        metric = ShapleyAttributionMetric(
            model, params, batches, cross_entropy_loss, state=state,
            sv_samples=5, seed=0, compute_dtype=jax.numpy.bfloat16,
        )
        scores = metric.run(target)
        res = prune_by_scores(model, params, target, scores,
                              policy="negative", state=state)
        model, params, state = res.model, res.params, res.state
    hard_fence(params)
    elapsed = time.perf_counter() - t0
    return {
        "value": round(elapsed, 3),
        "unit": "s",
        "vs_baseline": round(MNIST_BASELINE_S / elapsed, 3),
        "params_before": params_before,
        "params_after": param_count(params),
    }


def _leg_vgg_robustness(smoke: bool, progress=None) -> dict:
    """Leg 2: the FULL layerwise-robustness sweep — every prunable layer
    × the 8-method panel (3 runs for stochastic methods), measured end to
    end with no projection (reference: 6.5 h for 15 layers × 8 methods).
    ``progress`` (from run_leg) checkpoints after every layer so a kill
    mid-sweep still reports the finished layers' AUCs.

    The net is TRAINED first, in-leg, on digits32 (real sklearn digit
    scans at CIFAR-10 geometry — the only real image data guaranteed in
    the environment), so the AUC table reflects method quality on a
    genuinely trained net rather than noise on random weights.  Protocol
    deltas vs the reference (recorded in the output): 300 test examples
    instead of 1000, digits32 instead of CIFAR-10.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from torchpruner_tpu.data import load_dataset
    from torchpruner_tpu.experiments.robustness import (
        PANEL_VERSION,
        auc_summary_std,
        layerwise_robustness,
        method_panel,
    )
    from torchpruner_tpu.models import vgg16_bn
    from torchpruner_tpu.train.loop import Trainer
    from torchpruner_tpu.utils.losses import cross_entropy_loss
    from torchpruner_tpu.utils.profiling import hard_fence

    if smoke:
        model = vgg16_bn(width_multiplier=0.125, classifier_width=64)
        n_examples, bs, layers = 64, 32, ["conv8", "fc1"]
        epochs, train_bs = 1, 64
    else:
        model = vgg16_bn()
        # BENCH_ROBUSTNESS_EXAMPLES trades protocol fidelity for wall
        # clock (CPU fallback runs of the full-width sweep); the TPU
        # default is the full 300-example digits32 test split
        n_examples = int(os.environ.get("BENCH_ROBUSTNESS_EXAMPLES",
                                        "300"))
        bs, layers = n_examples, None  # None = all 15
        epochs, train_bs = 12, 128

    # -- train to non-degenerate accuracy (bf16 steps, real digit data;
    # -- cross-window resume (non-smoke): the full sweep outlasts the
    # tunnel's observed uptime windows, so trained weights + finished
    # layers persist under logs/ and a rerun continues where the last
    # attempt was killed instead of starting the multi-hour sweep over --
    import pickle

    resume_path = None if smoke else ROBUSTNESS_RESUME
    weights_path = (resume_path + ".weights") if resume_path else None
    # the scratch is only valid for the exact protocol that produced it:
    # geometry/examples/epochs AND the method panel (panel string bumped
    # whenever the methods dict / sv_samples / runs change)
    cfg_key = {"n_examples": n_examples, "epochs": epochs,
               "platform": jax.devices()[0].platform,
               "panel": PANEL_VERSION}

    def _atomic_pickle(path, obj):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(obj, f)
        os.replace(tmp, path)  # a kill mid-write can't tear the scratch

    resume = resume_weights = None
    if resume_path and os.path.exists(resume_path) \
            and os.path.exists(weights_path):
        try:
            with open(resume_path, "rb") as f:
                resume = pickle.load(f)
            with open(weights_path, "rb") as f:
                resume_weights = pickle.load(f)
            if resume.get("config") != cfg_key or \
                    resume_weights.get("config") != cfg_key:
                resume = resume_weights = None
        except Exception:
            resume = resume_weights = None

    # adam reaches >95% digits32 test acc by epoch ~4 where the
    # reference's SGD recipe, tuned for 150-epoch CIFAR, barely moves) --
    if resume_weights is not None:
        params = jax.tree_util.tree_map(jnp.asarray,
                                        resume_weights["params"])
        state = jax.tree_util.tree_map(jnp.asarray,
                                       resume_weights["state"])
        train_s = resume["train_s"]
    else:
        train = load_dataset("digits32", "train", seed=0)
        trainer = Trainer.create(model, optax.adam(1e-3),
                                 cross_entropy_loss, seed=0,
                                 compute_dtype=jnp.bfloat16)
        t0 = time.perf_counter()
        for epoch in range(epochs):
            for x, y in train.iter_batches(train_bs, shuffle=True,
                                           seed=epoch,
                                           drop_remainder=True):
                trainer.step(jnp.asarray(x), jnp.asarray(y))
        hard_fence(trainer.params)
        train_s = time.perf_counter() - t0
        params, state = trainer.params, trainer.state

    test = load_dataset("digits32", "test", n=n_examples, seed=0)
    batches = test.batches(bs)
    from torchpruner_tpu.train.loop import evaluate as eval_model
    test_loss, test_acc = eval_model(model, params, state, batches,
                                     cross_entropy_loss)

    # bf16 scoring forwards (MXU rate), f32 loss accumulation — the
    # TPU-native sweep configuration; ONE panel definition shared with
    # experiments.sweep_scaling (which calibrates this leg's
    # example-count adjustment)
    methods = method_panel(model, params, batches, cross_entropy_loss,
                           state=state, compute_dtype=jnp.bfloat16)
    from torchpruner_tpu.core.graph import pruning_graph

    all_layers = (list(layers) if layers is not None
                  else [g.target for g in pruning_graph(model)])
    done: dict = dict(resume["results"]) if resume else {}
    prior_wall_s = resume.get("wall_s", 0.0) if resume else 0.0
    remaining = [l for l in all_layers if l not in done]

    # the weights never change after training — write them ONCE (outside
    # the timed sweep), then checkpoint only the small per-layer results
    if weights_path and resume_weights is None:
        try:
            _atomic_pickle(weights_path, {
                "config": cfg_key,
                "params": jax.tree_util.tree_map(np.asarray, params),
                "state": jax.tree_util.tree_map(np.asarray, state),
            })
        except OSError:
            pass

    t0 = time.perf_counter()
    partial_results: dict = dict(done)

    def save_resume():
        if resume_path is None:
            return
        try:
            _atomic_pickle(resume_path, {
                "config": cfg_key,
                "train_s": train_s,
                "results": partial_results,
                "wall_s": prior_wall_s + time.perf_counter() - t0,
            })
        except OSError:
            pass

    save_resume()  # trained weights persist even if layer 1 is killed

    def on_layer(layer, layer_res):
        partial_results[layer] = layer_res
        save_resume()
        if progress is None:
            return
        stats = auc_summary_std(partial_results)
        progress({
            "value": None,
            "unit": "s",
            "layers_done": len(partial_results),
            "elapsed_s": round(
                prior_wall_s + time.perf_counter() - t0, 1),
            "eval_examples": len(test),
            "auc_so_far": {k: round(v["mean"], 4)
                           for k, v in stats.items()},
            "trained_test_acc": round(float(test_acc), 4),
        })

    new_results = layerwise_robustness(
        model, params, state, batches, methods, cross_entropy_loss,
        layers=remaining, compute_dtype=jnp.bfloat16, verbose=False,
        on_layer=on_layer,
    ) if remaining else {}
    merged = {**done, **new_results}
    results = {l: merged[l] for l in all_layers if l in merged}
    # wall clock accumulated over every attempt's sweep loop (training
    # time excluded, as before; repeated per-attempt compiles included —
    # that is the real cost of measuring over a flaky tunnel)
    sweep_s = prior_wall_s + time.perf_counter() - t0
    for p in (resume_path, weights_path):
        if p and os.path.exists(p):
            try:  # complete: a later run should measure fresh, not replay
                os.remove(p)
            except OSError:
                pass
    per_layer_s = {
        layer: round(sum(r["seconds"] for runs in by_method.values()
                         for r in runs), 2)
        for layer, by_method in results.items()
    }
    # scoring and ablation cost scale ~linearly in example count, so the
    # baseline comparison is stated at the reference's 1000-example
    # protocol (conservative: our 300-example measurement scaled up 10/3)
    adjusted_s = sweep_s * (1000.0 / max(1, len(test)))
    auc_stats = auc_summary_std(results)
    # one-pass capture engine accounting, next to the generic obs row the
    # leg wrapper attaches: hits/misses per scoring batch, the estimated
    # prefix FLOPs the cache avoided, and the compile bill of the
    # capture_fill span (CompileWatcher-attributed — the ≤2-prefix-
    # programs invariant CI asserts on the smoke preset)
    from torchpruner_tpu import obs as _obs

    capture_row = dict(_obs.capture_counts())
    _session = _obs.get()
    if _session is not None:
        fill = _session.tracer.phase_summary().get("capture_fill", {})
        capture_row["fill_compile_count"] = int(
            fill.get("compile_count", 0))
        capture_row["fill_s"] = round(fill.get("total_s", 0.0), 3)
        capture_row["fill_calls"] = int(fill.get("calls", 0))
    return {
        "capture": capture_row,
        "value": round(sweep_s, 1),
        "unit": "s",
        "vs_baseline": round(SWEEP_BASELINE_S / adjusted_s, 3),
        "projection": None,  # every layer measured, nothing extrapolated
        "n_layers": len(results),
        "panel_runs": SWEEP_PANEL_RUNS,
        "per_layer_seconds": per_layer_s,
        "eval_examples": len(test),
        "resumed_layers": len(done),
        "examples_adjusted_s": round(adjusted_s, 1),
        "compute_dtype": "bfloat16",
        "trained": {
            "dataset": "digits32 (real sklearn digits, 32x32x3)",
            "epochs": epochs,
            "train_seconds": round(train_s, 1),
            "test_acc": round(float(test_acc), 4),
            "test_loss": round(float(test_loss), 4),
        },
        "protocol_delta": f"{len(test)} digits32 test examples vs the "
                          "reference's 1000 CIFAR-10 examples; AUCs are "
                          "on a trained net and ranking-comparable; "
                          "vs_baseline uses the 1000-example-adjusted "
                          "wall-clock",
        # mean ± spread over the per-layer/per-run AUCs (the reference
        # reports its table as a 3-run mean, BASELINE.md)
        "auc": {k: round(v["mean"], 4) for k, v in auc_stats.items()},
        "auc_std": {k: round(v["std"], 4) for k, v in auc_stats.items()},
    }


def _leg_vgg_train(smoke: bool) -> dict:
    """Leg 3: steady-state VGG16-bn train-step time, img/s/chip, MFU."""
    import jax
    import numpy as np
    import optax

    from torchpruner_tpu.models import vgg16_bn
    from torchpruner_tpu.train.loop import Trainer
    from torchpruner_tpu.utils.flops import model_cost
    from torchpruner_tpu.utils.losses import cross_entropy_loss
    from torchpruner_tpu.utils.profiling import (
        steady_s,
        time_train_multi_step,
        time_train_step,
    )

    if smoke:
        model = vgg16_bn(width_multiplier=0.125, classifier_width=64)
        batch = 16
    else:
        model = vgg16_bn()
        batch = 256
    #: optimizer steps folded into ONE dispatched program (lax.scan over
    #: stacked batches): per-program dispatch cost amortizes 1/K — the
    #: round-4 gap (4.3 ms device step timed at 27+ ms) was dispatch,
    #: not device time (results/steptrace_vgg16_tpu_*)
    K = 4 if smoke else 8
    rng = np.random.default_rng(0)
    x = jax.numpy.asarray(
        rng.normal(size=(batch, 32, 32, 3)).astype("float32"))
    y = jax.numpy.asarray(
        rng.integers(0, 10, size=(batch,)).astype("int32"))
    peak = _peak_flops(jax.devices()[0])

    def measure(compute_dtype, with_mfu=True, with_dispatch=True,
                with_kernels=False):
        trainer = Trainer.create(model, optax.sgd(0.05, momentum=0.9),
                                 cross_entropy_loss, seed=0,
                                 compute_dtype=compute_dtype)
        out = {}
        compile_s = 0.0
        if with_dispatch:  # per-dispatch single step, for the gap story
            stats = time_train_step(trainer, x, y, iters=10, warmup=3,
                                    chained=True)
            out["ms_per_dispatch"] = round(steady_s(stats) * 1e3, 3)
            out["ms_fenced_p50"] = round(stats["p50_s"] * 1e3, 3)
            compile_s = stats["compile_s"]
        # the headline: K steps per dispatched program (how the train
        # loop SHOULD run on a remote/tunnelled device)
        xs = jax.numpy.stack([x] * K)
        ys = jax.numpy.stack([y] * K)
        mstats = time_train_multi_step(trainer, xs, ys, iters=4, warmup=2,
                                       chained=True)
        step_s = steady_s(mstats) / K
        out = {
            "ms": round(step_s * 1e3, 3),
            "steps_per_program": K,
            **out,
            "img_per_s_per_chip": round(batch / step_s, 1),
            "compile_s": round(compile_s + mstats["compile_s"], 2),
        }
        fwd_flops = None
        if with_mfu:
            _, fwd_flops = model_cost(model, trainer.params, trainer.state,
                                      batch_size=batch)
            if fwd_flops and peak:
                # fwd+bwd ≈ 3× forward FLOPs (standard approximation);
                # the peak table is bf16, so MFU only applies to that leg
                out["mfu"] = round((3.0 * fwd_flops / step_s) / peak, 4)
                _flag_implausible_mfu(out)
            else:
                out["mfu"] = None
        if with_kernels:
            # one post-measurement capture window over a representative
            # multi-step dispatch: top-5 kernel rows ride the leg row
            from torchpruner_tpu.utils.profiling import hard_fence

            with _kernel_window(out, steps=K,
                                flops_per_step=(3.0 * fwd_flops
                                                if fwd_flops else None)):
                hard_fence(trainer.multi_step(xs, ys)[-1])
        return out

    # bf16 compute is the TPU-native training config (the MFU denominator
    # is the chip's bf16 peak); f32 step time recorded alongside for
    # reference, without an MFU (its peak differs)
    bf16 = measure(jax.numpy.bfloat16, with_kernels=True)
    f32 = measure(None, with_mfu=False)
    out = {
        "value": bf16["ms"],
        "unit": "ms/step",
        "batch": batch,
        "compute_dtype": "bfloat16",
        "steps_per_program": bf16["steps_per_program"],
        "ms_per_dispatch": bf16["ms_per_dispatch"],
        "img_per_s_per_chip": bf16["img_per_s_per_chip"],
        "mfu": bf16["mfu"],
        "compile_s": bf16["compile_s"],
        "f32": f32,
        **({"kernels": bf16["kernels"]} if "kernels" in bf16 else {}),
    }
    try:
        # static cost model (analysis/cost_model.py): the roofline
        # prediction for this leg's bf16 step, printed next to the
        # measurement so prediction drift is visible in every bench row
        from torchpruner_tpu.analysis import cost_model

        pred = cost_model.predict_train_step(
            model, optax.sgd(0.05, momentum=0.9), cross_entropy_loss,
            batch=batch, compute_dtype=jax.numpy.bfloat16)
        if pred is not None:
            out["predicted_step_ms"] = round(pred.step_ms, 3)
            out["predicted_comm_ms"] = round(pred.comm_ms, 3)
            out["predicted_bound"] = pred.bound
    except Exception:
        pass
    if not smoke and jax.devices()[0].platform == "tpu":
        # batch scaling: small 32x32 convs underfill the MXU at b256, so
        # sweep larger batches and surface the best-MFU configuration
        def measure_at(b):
            nonlocal x, y, batch
            x = jax.numpy.asarray(
                rng.normal(size=(b, 32, 32, 3)).astype("float32"))
            y = jax.numpy.asarray(
                rng.integers(0, 10, size=(b,)).astype("int32"))
            batch = b  # measure() closes over batch for img/s + MFU
            # sweep points skip the single-step dispatch timing (its
            # only product is ms_per_dispatch, which the sweep drops)
            r = measure(jax.numpy.bfloat16, with_dispatch=False)
            keep = {"ms": r["ms"], "mfu": r["mfu"],
                    "img_per_s_per_chip": r["img_per_s_per_chip"]}
            if "implausible" in r:
                keep["implausible"] = r["implausible"]
            return keep

        seeded = {batch: {"ms": bf16["ms"], "mfu": bf16["mfu"],
                          "img_per_s_per_chip": bf16["img_per_s_per_chip"],
                          **({"implausible": bf16["implausible"]}
                             if "implausible" in bf16 else {})}}
        sweep = _batch_sweep(measure_at, seeded, (512, 1024, 2048))
        out["batch_sweep"] = {str(b): v for b, v in sweep.items()}
        best = max(
            (v for v in sweep.values()
             if v.get("mfu") and "implausible" not in v),
            key=lambda v: v["mfu"], default=None,
        )
        if best:
            out["best_mfu"] = best["mfu"]
    return out


def _flag_implausible_mfu(r: dict) -> dict:
    from torchpruner_tpu.utils.flops import flag_implausible_mfu

    return flag_implausible_mfu(r)


def _batch_sweep(measure, seeded: dict, batches) -> dict:
    """Extend ``{batch: result}`` with ``measure(b)`` per extra batch
    size (shared by the VGG and mfu_llama MFU sweeps).  A failure —
    typically HBM OOM — records an error cell and ENDS the sweep: larger
    batches would only fail harder."""
    sweep = dict(seeded)
    for b in batches:
        try:
            sweep[b] = measure(b)
        except Exception as e:  # noqa: BLE001 - OOM ends the sweep
            sweep[b] = {"error": f"{type(e).__name__}: {e}"[:200]}
            break
    return sweep


def _leg_mfu_llama(smoke: bool) -> dict:
    """MFU ceiling check on a matmul-dominated workload: train-step MFU
    for a ~200M-param Llama (dim 1024 × depth 8, 32k vocab, S=1024).
    VGG16 on 32×32 images is conv-bound with tiny spatial dims — this leg
    shows what fraction of peak the same Trainer/step machinery reaches
    when the FLOPs live in large MXU-shaped matmuls."""
    import jax
    import numpy as np
    import optax

    from torchpruner_tpu.models import llama_tiny, mfu_llama
    from torchpruner_tpu.train.loop import Trainer
    from torchpruner_tpu.utils.flops import model_cost, param_count
    from torchpruner_tpu.utils.losses import lm_cross_entropy_loss
    from torchpruner_tpu.utils.profiling import (
        steady_s,
        time_train_multi_step,
        time_train_step,
    )

    if smoke:
        model, B = llama_tiny(), 2
    else:
        # one factory shared with experiments.step_trace --model
        # mfu_llama, so the stopwatch and the trace profile the same net
        model, B = mfu_llama(), 8
    S = model.input_shape[0]
    rng = np.random.default_rng(0)
    peak = _peak_flops(jax.devices()[0])

    # one Trainer for the whole sweep: params/opt-state are
    # batch-independent (re-initializing ~200M params per batch size
    # would waste a third of the leg's budget); jit recompiles the step
    # per token shape either way
    trainer = Trainer.create(model, optax.adam(3e-4),
                             lm_cross_entropy_loss, seed=0,
                             compute_dtype=jax.numpy.bfloat16)
    params = param_count(trainer.params)

    # steps folded into one dispatched program (see _leg_vgg_train's K):
    # llama steps are big enough that dispatch costs less, but the
    # amortization still removes the residual per-step overhead
    K = 2 if smoke else 4

    def measure(b, with_dispatch=True):
        toks = jax.numpy.asarray(
            rng.integers(0, 1000, size=(b, S)).astype("int32"))
        r = {}
        compile_s = 0.0
        if with_dispatch:
            stats = time_train_step(trainer, toks, toks, iters=10,
                                    warmup=3, chained=True)
            # chained = async-dispatch steady state (how the train loop
            # runs); fenced p50 carries a tunnel round trip per step
            r["ms_per_dispatch"] = round(steady_s(stats) * 1e3, 3)
            r["ms_fenced_p50"] = round(stats["p50_s"] * 1e3, 3)
            compile_s = stats["compile_s"]
        xs = jax.numpy.stack([toks] * K)
        mstats = time_train_multi_step(trainer, xs, xs, iters=4, warmup=2,
                                       chained=True)
        step_s = steady_s(mstats) / K
        r = {
            "ms": round(step_s * 1e3, 3),
            "steps_per_program": K,
            **r,
            "tokens_per_s_per_chip": round(b * S / step_s, 1),
            "compile_s": round(compile_s + mstats["compile_s"], 2),
        }
        _, fwd_flops = model_cost(model, trainer.params, trainer.state,
                                  batch_size=b)
        r["mfu"] = (round((3.0 * fwd_flops / step_s) / peak, 4)
                    if fwd_flops and peak else None)
        return _flag_implausible_mfu(r)

    first = measure(B)
    out = {
        **first,
        "params": params,
        "shape": f"B{B} S{S}",
        "compute_dtype": "bfloat16",
    }
    if not smoke and jax.devices()[0].platform == "tpu":
        # MFU rises with arithmetic intensity until HBM runs out — sweep
        # batch and surface the best configuration (the number the ≥35%
        # target is judged on)
        sweep = _batch_sweep(lambda b: measure(b, with_dispatch=False),
                             {B: first}, (16, 32, 64))
        out["batch_sweep"] = {str(b): v for b, v in sweep.items()}
        best = max((v for v in sweep.values()
                    if v.get("mfu") and "implausible" not in v),
                   key=lambda v: v["mfu"], default=None)
        if best:
            out["best_mfu"] = best["mfu"]
            out["best_tokens_per_s_per_chip"] = best["tokens_per_s_per_chip"]
    return out


def _leg_flash_attention(smoke: bool) -> dict:
    """Flash (Pallas fwd+bwd kernels on TPU, the blocked lax form
    elsewhere) vs XLA einsum attention: steady-state grad-step time and
    compiled temp memory at long sequence length — the O(S*Dh) vs
    O(S^2) backward-memory claim, measured.  On TPU the headline shape
    is AUTOTUNED first (ops/autotune.py: a quick block-size sweep whose
    winner persists in the tuning cache), so the measured row is the
    tuned kernel — the ≥1.3x @ S≥8k target ROADMAP item 2 sets."""
    import jax
    import jax.numpy as jnp

    from torchpruner_tpu.ops import autotune
    from torchpruner_tpu.ops.flash_attention import (
        _xla_attention,
        flash_attention,
    )
    from torchpruner_tpu.utils.profiling import steady_s, time_fn

    on_tpu = jax.devices()[0].platform == "tpu"

    def make(fn, **fkw):
        def loss(q_, k_, v_):
            return jnp.sum(
                fn(q_, k_, v_, causal=True, **fkw).astype(jnp.float32))
        return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    def measure(B, S, H, Dh, tune=False):
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q, k, v = (jax.random.normal(kk, (B, S, H, Dh), jnp.bfloat16)
                   for kk in ks)
        r = {"impl": "pallas" if on_tpu else "lax"}
        if tune and on_tpu:
            def run(blocks):
                g = make(flash_attention, block_q=blocks[0],
                         block_k=blocks[1])
                return lambda: g(q, k, v)

            tuned = autotune.autotune(
                autotune.KIND_FLASH, Dh, S, q.dtype, run=run,
                candidates=((128, 128), (128, 256), (256, 128),
                            (128, 512), (256, 256)),
                defaults=(128, 256 if S >= 8192 else 128), iters=3)
            r["tuned_blocks"] = list(tuned)
        gs = {}
        for name, fn in (("flash", flash_attention),
                         ("xla", _xla_attention)):
            g = gs[name] = make(fn)
            stats = time_fn(g, q, k, v, iters=5, warmup=2, chained=True)
            r[f"{name}_ms"] = round(steady_s(stats) * 1e3, 3)
            r[f"{name}_ms_fenced_p50"] = round(stats["p50_s"] * 1e3, 3)
            try:
                mem = g.lower(q, k, v).compile().memory_analysis()
                r[f"{name}_temp_mb"] = round(
                    mem.temp_size_in_bytes / 2**20, 1)
            except Exception:
                r[f"{name}_temp_mb"] = None
        if r.get("xla_ms") and r.get("flash_ms"):
            r["speedup"] = round(r["xla_ms"] / r["flash_ms"], 3)
        r["shape"] = f"B{B} S{S} H{H} Dh{Dh} bf16 causal"
        # which ops the flash grad step actually spends its ms in — the
        # evidence the 0.983x-vs-XLA retune needs (ROADMAP item 2)
        with _kernel_window(r, steps=1):
            jax.block_until_ready(gs["flash"](q, k, v))
        return r

    if smoke:
        # S=1024/Dh64: past the CPU cache cliff where the einsum's S^2
        # scores stop fitting — the blocked path's win is decisive
        # (smaller S measures allocator noise, not the algorithm)
        return measure(1, 1024, 4, 64)
    if not on_tpu:
        return measure(4, 2048, 8, 64)  # CPU fallback (lax path)
    # headline at S=8192 — a shape where impl="auto" actually dispatches
    # the kernel (S >= FLASH_AUTO_MIN_S) and its linear backward memory
    # matters; the old S=2048 headline showcased the XLA fallback the
    # auto dispatch deliberately picks there (round-4 verdict).  The
    # crossover point stays measured as the secondary row; the full S
    # curve lives in results/flash_sweep_tpu_*.
    out = measure(4, 8192, 8, 64, tune=True)
    out["crossover_s2048"] = measure(4, 2048, 8, 64)
    return out


def _leg_blocksparse(smoke: bool) -> dict:
    """Leg: structured sparsity the kernel inner loop can SEE.  A
    50%-block-dropped weight (the ``score_drop_indices(granularity=128)``
    mask shape) is multiplied three ways on the SAME shapes: the
    block-sparse Pallas kernel (skips dropped blocks), the same kernel
    dense (all blocks — the apples-to-apples machinery baseline), and
    the dense XLA matmul; plus a FULL train step on a Dense MLP, masked-
    dense vs block-sparse-dispatched (train.loop ``param_transform``) —
    the ms/step number that used to move only in the FLOPs gauge."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    from torchpruner_tpu.core import layers as L
    from torchpruner_tpu.core import masking
    from torchpruner_tpu.core.pruner import score_drop_indices
    from torchpruner_tpu.core.segment import SegmentedModel, init_model
    from torchpruner_tpu.ops.blocksparse import blocksparse_matmul
    from torchpruner_tpu.train.loop import make_train_step
    from torchpruner_tpu.utils.losses import cross_entropy_loss
    from torchpruner_tpu.utils.profiling import steady_s, time_fn

    block = 128
    R, D, F = (256, 1024, 1024) if smoke else (1024, 4096, 4096)
    x = jax.random.normal(jax.random.PRNGKey(0), (R, D), jnp.bfloat16)
    w = np.array(jax.random.normal(jax.random.PRNGKey(1), (D, F)),
                 np.float32)
    in_keep = tuple(range(0, D // block, 2))   # 50% of input blocks
    out_keep = tuple(range(0, F // block, 2))  # 50% of output blocks
    for b in range(D // block):
        if b not in in_keep:
            w[b * block:(b + 1) * block] = 0
    for b in range(F // block):
        if b not in out_keep:
            w[:, b * block:(b + 1) * block] = 0
    wb = jnp.asarray(w, jnp.bfloat16)
    variants = {
        "sparse_kernel": jax.jit(lambda a, b: blocksparse_matmul(
            a, b, in_keep=in_keep, out_keep=out_keep, block=block)),
        "dense_kernel": jax.jit(lambda a, b: blocksparse_matmul(
            a, b, block=block)),
        "dense_xla": jax.jit(lambda a, b: a @ b),
    }
    r = {"block": block, "shape": f"R{R} D{D} F{F}", "sparsity": 0.5}
    for name, fn in variants.items():
        stats = time_fn(fn, x, wb, iters=5, warmup=2, chained=True)
        r[f"{name}_ms"] = round(steady_s(stats) * 1e3, 3)
    r["sparse_vs_dense_kernel"] = round(
        r["dense_kernel_ms"] / r["sparse_kernel_ms"], 3)
    r["sparse_vs_dense_xla"] = round(
        r["dense_xla_ms"] / r["sparse_kernel_ms"], 3)

    # full-train-step integration: masked-dense vs kernel-dispatched on
    # the same masked params (identical numerics — tests pin it)
    width = 512 if smoke else 2048
    model = SegmentedModel([
        L.Dense("fc1", 64, width), L.Activation("a1", "relu"),
        L.Dense("fc2", width, width), L.Activation("a2", "relu"),
        L.Dense("out", width, 10),
    ], input_shape=(64,))
    params, state = init_model(model, seed=0)
    scores = np.asarray(jax.random.normal(jax.random.PRNGKey(2), (width,)))
    drop = score_drop_indices(scores, policy="fraction", fraction=0.5,
                              granularity=block)
    drops = {"fc2": drop}
    masks, _ = masking.drop_masks(model, params, drops, state=state)
    mp = masking.apply_masks(params, masks)
    tx = optax.chain(optax.sgd(0.05), masking.masked_update(masks))
    xb = jax.random.normal(jax.random.PRNGKey(3), (R, 64))
    yb = np.asarray(
        jax.random.randint(jax.random.PRNGKey(4), (R,), 0, 10))
    rng = jax.random.PRNGKey(5)

    def step_ms(param_transform):
        step = make_train_step(model, tx, cross_entropy_loss,
                               donate=False,
                               param_transform=param_transform)
        o = tx.init(mp)
        stats = time_fn(step, mp, state, o, xb, yb, rng, iters=5,
                        warmup=2, chained=True)
        return round(steady_s(stats) * 1e3, 3)

    r["train_step_masked_dense_ms"] = step_ms(None)
    r["train_step_blocksparse_ms"] = step_ms(
        lambda p: masking.blocksparse_params(model, p, drops, block=block))
    r["train_step_ms_saved"] = round(
        r["train_step_masked_dense_ms"]
        - r["train_step_blocksparse_ms"], 3)
    r["train_step_speedup"] = round(
        r["train_step_masked_dense_ms"]
        / max(r["train_step_blocksparse_ms"], 1e-9), 3)
    # headline: the measured ms reduction 50% structured sparsity buys
    # through the SAME kernel machinery on the same shapes — positive on
    # every backend.  The vs-XLA train-step comparison is only
    # meaningful on chip (the CPU interpreter pays a per-block python
    # dispatch the MXU pipeline doesn't); scripts/capture_tpu.sh's
    # staged assertion holds that line when the tunnel returns.
    r["value"] = r["sparse_vs_dense_kernel"]
    r["unit"] = "x_vs_dense_same_kernel_at_50pct_sparsity"
    with _kernel_window(r, steps=1):
        jax.block_until_ready(variants["sparse_kernel"](x, wb))
    return r


def _leg_llama_decode(smoke: bool, progress=None) -> dict:
    """KV-cache decode throughput (tokens/s) on the llama family, dense
    AND after a 25% FFN-channel prune (example 04's serving flow) — the
    speedup structured pruning actually buys at decode time (no
    reference baseline; the reference has no inference loop).

    ``progress`` checkpoints after every sub-measurement (dense, bf16-KV,
    pruned, int8) — this leg wedged a full tunnel window once, losing the
    dense number it had already measured."""
    import jax
    import numpy as np

    from torchpruner_tpu.core.segment import init_model
    from torchpruner_tpu.generate import generate
    from torchpruner_tpu.models import llama_tiny, mfu_llama
    from torchpruner_tpu.utils.profiling import hard_fence

    on_tpu = jax.devices()[0].platform == "tpu"
    if smoke:
        model, B, S, n_new = llama_tiny(), 2, 8, 16
    elif on_tpu:
        # serving-scale: a ~200M-param model's decode is HBM-bound (reads
        # all params per token) — the number that means something; the
        # 35k-param tiny model only measures per-step launch overhead
        model, B, S, n_new = mfu_llama(), 8, 64, 128
    else:
        model, B, S, n_new = llama_tiny(), 8, 64, 128
    params, _ = init_model(model, seed=0)
    prompt = np.asarray(
        jax.random.randint(jax.random.PRNGKey(0), (B, S), 0, 256), np.int32
    )
    reps = 1 if smoke else 10

    def timed_decode(m_, p_, **kw):
        # chained like time_fn(chained=True): the per-call canary fence
        # pays a tunnel RTT comparable to a whole 128-token decode, which
        # would mask the pruned/int8 deltas this leg exists to measure
        t0 = time.perf_counter()
        out = None
        for _ in range(reps):
            out = generate(m_, p_, prompt, n_new, **kw)
        hard_fence(out)
        return (time.perf_counter() - t0) / reps

    t0 = time.perf_counter()
    out = generate(model, params, prompt, n_new)
    hard_fence(out)
    compile_and_first = time.perf_counter() - t0
    steady = timed_decode(model, params)
    # end-to-end generation throughput: GENERATED tokens over the whole
    # call (the one-shot prefill's cost sits in the denominator, not the
    # numerator — counting prompt positions would inflate the rate)
    result = {
        "gen_tokens_per_s": round(B * n_new / steady, 1),
        "steady_s": round(steady, 3),
        "first_call_s": round(compile_and_first, 2),
        "model": ("mfu_llama (~200M)" if not smoke and on_tpu
                  else "llama_tiny"),
        "shape": f"B{B} prompt{S} new{n_new}",
    }
    result["measured_ms_per_step"] = round(1e3 * steady / n_new, 3)
    try:
        # static cost model: predicted decode-step time (one token for
        # all B rows) next to the measured per-token step — drift between
        # the two is the lint-grade honesty check PERF.md documents
        from torchpruner_tpu.analysis import cost_model

        pred = cost_model.predict_decode(model, n_slots=B,
                                         max_len=S + n_new)
        if pred is not None:
            result["predicted_step_ms_decode"] = round(pred.step_ms, 3)
            result["predicted_comm_ms_decode"] = round(pred.comm_ms, 3)
    except Exception:
        pass
    # one capture window over a dense decode: per-token kernel table
    # (steps = generated tokens, so ms_per_step reads as ms/token)
    with _kernel_window(result, steps=n_new):
        hard_fence(generate(model, params, prompt, n_new))
    if progress is not None:
        progress(dict(result))
    if not smoke and on_tpu:
        # bf16 KV cache: the serving configuration (half the cache bytes;
        # decode is HBM-bandwidth-bound so it reads half as much).  TPU
        # only — the extra compile buys nothing on the CPU fallback.
        import jax.numpy as jnp

        hard_fence(generate(model, params, prompt, n_new,
                            cache_dtype=jnp.bfloat16))  # compile
        steady16 = timed_decode(model, params, cache_dtype=jnp.bfloat16)
        result["gen_tokens_per_s_bf16_cache"] = round(
            B * n_new / steady16, 1)
        if progress is not None:
            progress(dict(result))
    # post-prune serving (example 04's flow, scoring cost excluded):
    # weight_norm-score every block's FFN channels, prune the lowest 25%,
    # decode at the pruned shapes — the structured-prune decode payoff.
    # Not in smoke: the extra prune + generate compiles buy no validation
    # the quant/pruner test files don't already provide.
    if smoke:
        return result
    from torchpruner_tpu.attributions import WeightNormAttributionMetric
    from torchpruner_tpu.core.graph import pruning_graph
    from torchpruner_tpu.core.pruner import prune_by_scores
    from torchpruner_tpu.utils.flops import param_count
    from torchpruner_tpu.utils.losses import lm_cross_entropy_loss

    params_before = param_count(params)
    pm, pp, ps = model, params, None
    for g in pruning_graph(model):
        if not g.target.endswith("/gate"):  # FFN hidden channels only
            continue
        scores = WeightNormAttributionMetric(
            pm, pp, [], lm_cross_entropy_loss).run(g.target)
        res = prune_by_scores(pm, pp, g.target, scores,
                              policy="fraction", fraction=0.25, state=ps)
        pm, pp, ps = res.model, res.params, res.state
    hard_fence(generate(pm, pp, prompt, n_new))  # compile
    steady_pruned = timed_decode(pm, pp)
    result["pruned_ffn_fraction"] = 0.25
    result["params_before"] = params_before
    result["params_after"] = param_count(pp)
    result["gen_tokens_per_s_pruned"] = round(B * n_new / steady_pruned, 1)
    result["prune_decode_speedup"] = round(steady / steady_pruned, 3)
    if progress is not None:
        progress(dict(result))
    if on_tpu:  # smoke already returned above
        # int8 weight-only serving (ops/quant.py): decode reads every
        # param per token, so halving weight bytes vs bf16 is the lever —
        # measured on the dense model AND the full prune->quantize deploy
        from torchpruner_tpu.ops.quant import quantize_params
        from torchpruner_tpu.utils.dtypes import cast_floats

        steady_q = {}
        # bf16-policy variants decode with a bf16 KV CACHE: at bf16/int
        # weights the f32 cache would double decode HBM reads for no
        # accuracy reason (generate.init_cache plumbs the dtype); the
        # f32-weights baselines above keep the f32 cache so the dense
        # numbers stay comparable with earlier rounds
        kv16 = {"cache_dtype": jax.numpy.bfloat16}
        # int4 runs with ALL-bf16 float leaves so the Dense/GatedDense
        # matmuls take the fused-unpack kernel path (quant.qdot);
        # attention projections unpack through XLA - the measured number
        # is the honest mix, not the kernel's best case.  Its divisor is
        # a bf16-weights DENSE baseline measured in the same activation
        # regime - dividing by the f32 dense baseline would conflate the
        # bf16 activation/MXU win with the int4 weight win
        pb16 = cast_floats(params, jax.numpy.bfloat16)
        hard_fence(generate(model, pb16, prompt, n_new, **kv16))  # compile
        steady_bf16w = timed_decode(model, pb16, **kv16)
        result["gen_tokens_per_s_bf16_weights"] = round(
            B * n_new / steady_bf16w, 1)
        result["kv_cache_dtype_quant_legs"] = "bfloat16"
        if progress is not None:
            progress(dict(result))
        for tag, (m_, p_, kw) in (
                ("int8", (model, params, {})),
                ("pruned_int8", (pm, pp, {})),
                ("int4", (model, params, {"bits": 4})),
                ("pruned_int4", (pm, pp, {"bits": 4}))):
            qp = quantize_params(m_, p_, **kw)
            if kw.get("bits") == 4:
                qp = cast_floats(qp, jax.numpy.bfloat16)
            hard_fence(generate(m_, qp, prompt, n_new, **kv16))  # compile
            steady_q[tag] = timed_decode(m_, qp, **kv16)
            result[f"gen_tokens_per_s_{tag}"] = round(
                B * n_new / steady_q[tag], 1)
            if progress is not None:
                progress(dict(result))
        result["int8_decode_speedup"] = round(steady / steady_q["int8"], 3)
        result["int4_decode_speedup_vs_bf16_weights"] = round(
            steady_bf16w / steady_q["int4"], 3)
        # the full deploy pipeline (prune 25% FFN -> int4) vs the plain
        # bf16-weights dense serving baseline
        result["pruned_int4_decode_speedup_vs_bf16_weights"] = round(
            steady_bf16w / steady_q["pruned_int4"], 3)
    return result


def _leg_serve(smoke: bool, progress=None) -> dict:
    """Leg: the continuous-batching serving engine (serve/) under
    open-loop Poisson traffic — the number ROADMAP item 1 asks for:
    sustained generated tok/s and TTFT / per-token tail latency of the
    multi-tenant decode path, not the static-batch ceiling.

    Two phases on ONE engine (so the measured phase pays no compiles):
    a step-staggered warmup that compiles prefill buckets + the decode
    step and measures the engine's closed-loop token capacity, then the
    measured open-loop phase at ~70% of that capacity (an arrival rate
    the engine can sustain — tail latency at a stable operating point;
    an overloaded open loop measures queue growth, not the engine)."""
    import jax
    import numpy as np

    from torchpruner_tpu.core.segment import init_model
    from torchpruner_tpu.models import llama_tiny, mfu_llama
    from torchpruner_tpu.serve import (
        ServeEngine,
        open_loop,
        synthetic_requests,
        vocab_of,
    )

    on_tpu = jax.devices()[0].platform == "tpu"
    if smoke:
        model, slots, max_len = llama_tiny(), 2, 96
        n, prompt_lens, max_new = 8, [4, 8], [8, 12]
    elif on_tpu:
        # serving-scale (~200M model, decode HBM-bound) — same model as
        # the llama_decode leg so the two rows are comparable
        model, slots, max_len = mfu_llama(), 8, 512
        n, prompt_lens, max_new = 64, [32, 64, 96], [64, 128]
    else:
        model, slots, max_len = llama_tiny(), 4, 256
        n, prompt_lens, max_new = 32, [8, 16, 24], [32, 48]
    params, _ = init_model(model, seed=0)
    vocab = vocab_of(model)

    eng = ServeEngine(model, params, n_slots=slots, max_len=max_len,
                      cache_dtype=jax.numpy.bfloat16 if on_tpu else None)
    warm_n = slots * 2
    warm = synthetic_requests(warm_n, vocab=vocab,
                              prompt_lens=prompt_lens, max_new=max_new,
                              seed=0)
    t0 = time.perf_counter()
    eng.run(open_loop(warm, stagger_steps=1))
    warm_s = time.perf_counter() - t0
    # capacity from a SECOND warm pass (same shapes, zero compiles) —
    # the first pass's wall is dominated by the compile bill
    cal = synthetic_requests(warm_n, vocab=vocab,
                             prompt_lens=prompt_lens, max_new=max_new,
                             seed=3)
    t0 = time.perf_counter()
    eng.run(open_loop(cal, stagger_steps=1))
    capacity = sum(len(r.tokens) for r in cal) \
        / max(time.perf_counter() - t0, 1e-9)
    result = {
        "warmup_requests": warm_n,
        "compile_and_warmup_s": round(warm_s, 2),
        "capacity_gen_tok_s": round(capacity, 1),
        "slots": slots,
        "model": "mfu_llama (~200M)" if (on_tpu and not smoke)
                 else "llama_tiny",
    }
    # one capture window over a short warm pass (same compiled
    # programs, zero compiles): the continuous-batching step's kernel
    # mix, BEFORE the measured phase so trace overhead stays out of it
    cap_reqs = synthetic_requests(slots, vocab=vocab,
                                  prompt_lens=prompt_lens,
                                  max_new=max_new, seed=7)
    steps0 = eng.steps
    with _kernel_window(result) as win:
        eng.run(open_loop(cap_reqs, stagger_steps=1))
        win.steps = max(1, eng.steps - steps0)
    if progress is not None:
        progress(dict(result))

    mean_new = float(np.mean(max_new))
    rate = 0.7 * capacity / mean_new  # requests/s at 70% utilization
    reqs = synthetic_requests(n, vocab=vocab, prompt_lens=prompt_lens,
                              max_new=max_new, seed=1)
    # measured-phase deltas: the warmup/calibration passes ran on the
    # SAME engine (shared compiles), so lifetime counters must be
    # rebased to report this phase alone
    evict0 = eng.scheduler.allocator.total_evictions
    steps0 = eng.steps
    # latency numbers come from the steady-state windows of a private
    # time-series recorder scoped to the measured phase (warmup windows
    # dropped), not whole-run means — the open loop's ramp-up otherwise
    # drags the percentiles.  The session recorder (if any) is swapped
    # out so the bench-wide series isn't polluted with leg-local windows.
    import tempfile as _tempfile

    from torchpruner_tpu import obs as _obs
    from torchpruner_tpu.obs.timeseries import (
        TimeseriesRecorder,
        steady_state_percentiles,
    )

    _sess = _obs.get()
    ts_dir = ts_rec = old_rec = None
    if _sess is not None:
        try:
            ts_dir = _tempfile.mkdtemp(prefix="bench_serve_ts_")
            ts_rec = TimeseriesRecorder(_sess.metrics, ts_dir,
                                        interval_s=0.2)
            old_rec = _sess.timeseries
            _sess.timeseries = ts_rec
        except Exception:  # noqa: BLE001 — telemetry never breaks bench
            ts_dir = ts_rec = None
    t0 = time.perf_counter()
    try:
        eng.run(open_loop(reqs, rate=rate, seed=2))
    finally:
        if ts_rec is not None:
            _sess.timeseries = old_rec
            try:
                ts_rec.close()
            except Exception:  # noqa: BLE001
                ts_dir = None
    wall = time.perf_counter() - t0
    done = [r for r in reqs if r.state == "done"]
    ttfts = np.asarray([r.ttft_s for r in done if r.ttft_s is not None])
    gaps = np.asarray([g for r in done for g in r.token_gaps_s])
    tokens = sum(len(r.tokens) for r in done)
    result.update({
        "requests": n,
        "requests_completed": len(done),
        "offered_rate_req_s": round(rate, 2),
        "gen_tokens": tokens,
        "value": round(tokens / wall, 1),
        "unit": "sustained_gen_tok_per_s",
        "ttft_p50_ms": round(float(np.percentile(ttfts, 50)) * 1e3, 2),
        "ttft_p99_ms": round(float(np.percentile(ttfts, 99)) * 1e3, 2),
        "token_p50_ms": round(float(np.percentile(gaps, 50)) * 1e3, 3),
        "token_p99_ms": round(float(np.percentile(gaps, 99)) * 1e3, 3),
        "evictions": eng.scheduler.allocator.total_evictions - evict0,
        "decode_steps": eng.steps - steps0,
    })
    # prefer the steady-state-window percentiles when the measured
    # phase produced enough windows (whole-run numbers above stay as
    # the fallback for very short smoke runs)
    if ts_dir is not None:
        steady = {}
        for metric, label in (("serve_ttft_seconds", "ttft"),
                              ("serve_token_seconds", "token")):
            seg = steady_state_percentiles(ts_dir, metric)
            if seg and seg.get("p50") is not None:
                steady[label] = seg
        for label, seg in steady.items():
            result[f"{label}_p50_ms"] = round(seg["p50"] * 1e3, 3)
            result[f"{label}_p99_ms"] = round(seg["p99"] * 1e3, 3)
        if steady:
            result["latency_source"] = "steady_state_windows"
            result["steady_obs_n"] = max(
                int(s.get("n") or 0) for s in steady.values())
    # latency budget at the 70%-load operating point: where TTFT time
    # actually went (queue wait vs admit-batch wait vs the prefill
    # program), from the per-request stage stamps — the top-2
    # contributors ride next to the p50/p99 columns
    ttft_sum = float(ttfts.sum()) if ttfts.size else 0.0
    if ttft_sum > 0:
        queue_s = sum(max(0.0, r.admitted_s - r.arrival_s) for r in done
                      if r.admitted_s is not None
                      and r.arrival_s is not None)
        prefill_s = sum(r.prefill_s or 0.0 for r in done)
        parts = {"replica_queue": queue_s, "prefill": prefill_s,
                 "admission": max(0.0, ttft_sum - queue_s - prefill_s)}
        top2 = sorted(parts.items(), key=lambda kv: -kv[1])[:2]
        result["ttft_budget_top2"] = [
            [k, round(100.0 * v / ttft_sum, 1)] for k, v in top2]
    if progress is not None:
        progress(dict(result))
    return result


def _leg_serve_prefix(smoke: bool, progress=None) -> dict:
    """Leg (``--prefix-heavy`` opt-in): Serve v2's prefix-sharing KV
    cache + chunked prefill under a prefix-heavy workload — the SAME
    shared-system-prompt traffic run twice on identical geometry,
    sharing ON then sharing OFF, on deterministic staggered arrivals
    (the comparison is prefill COMPUTE, so wall-clock arrival jitter
    is noise).  Value = prefill-token reduction factor (off/on); the
    row also carries the hit-rate, tokens served from shared pages,
    and both runs' TTFT p50/p99 — steady-state TTFT is where the
    saved prefill actually shows up (admission-to-first-token skips
    the shared pages' forward entirely)."""
    import jax
    import numpy as np

    from torchpruner_tpu.core.segment import init_model
    from torchpruner_tpu.models import llama_tiny, mfu_llama
    from torchpruner_tpu.serve import (
        ServeEngine,
        open_loop,
        shared_prefix_requests,
        vocab_of,
    )

    on_tpu = jax.devices()[0].platform == "tpu"
    if smoke:
        model, slots, max_len = llama_tiny(), 2, 96
        n, n_prefixes, prefix_len = 12, 2, 16
        suffix_lens, max_new = [4, 8], [8, 12]
        page, chunk, cap, pool = 8, 8, 16, 16
    elif on_tpu:
        model, slots, max_len = mfu_llama(), 8, 512
        n, n_prefixes, prefix_len = 48, 4, 128
        suffix_lens, max_new = [32, 64], [64, 128]
        page, chunk, cap, pool = 128, 128, 256, 32
    else:
        model, slots, max_len = llama_tiny(), 4, 256
        n, n_prefixes, prefix_len = 24, 3, 64
        suffix_lens, max_new = [8, 16], [16, 24]
        page, chunk, cap, pool = 8, 8, 32, 32
    params, _ = init_model(model, seed=0)
    vocab = vocab_of(model)
    result = {"requests": n, "n_prefixes": n_prefixes,
              "prefix_len": prefix_len, "slots": slots,
              "page_len": page, "prefill_chunk": chunk,
              "prefill_token_cap": cap, "prefix_pages": pool,
              "model": "mfu_llama (~200M)" if (on_tpu and not smoke)
                       else "llama_tiny"}

    def run(prefix_pages: int) -> dict:
        import tempfile as _tempfile

        from torchpruner_tpu import obs as _obs
        from torchpruner_tpu.obs.timeseries import (
            TimeseriesRecorder,
            steady_state_percentiles,
        )

        eng = ServeEngine(
            model, params, n_slots=slots, max_len=max_len,
            page_len=page, prefix_pages=prefix_pages,
            prefill_chunk=chunk, prefill_token_cap=cap,
            cache_dtype=jax.numpy.bfloat16 if on_tpu else None)
        reqs = shared_prefix_requests(
            n, vocab=vocab, n_prefixes=n_prefixes,
            prefix_len=prefix_len, suffix_lens=suffix_lens,
            max_new=max_new, seed=1)
        # per-run private PR 17 recorder (0.2 s windows) — the leg's
        # TTFT p50/p99 prefer the steady-state windows over whole-run
        # stamps, same contract as the base serve leg
        _sess = _obs.get()
        ts_dir = ts_rec = old_rec = None
        if _sess is not None:
            try:
                ts_dir = _tempfile.mkdtemp(prefix="bench_prefix_ts_")
                ts_rec = TimeseriesRecorder(_sess.metrics, ts_dir,
                                            interval_s=0.2)
                old_rec = _sess.timeseries
                _sess.timeseries = ts_rec
            except Exception:  # noqa: BLE001 — telemetry never breaks bench
                ts_dir = ts_rec = None
        t0 = time.perf_counter()
        try:
            eng.run(open_loop(reqs, stagger_steps=2))
        finally:
            if ts_rec is not None:
                _sess.timeseries = old_rec
                try:
                    ts_rec.close()
                except Exception:  # noqa: BLE001
                    ts_dir = None
        wall = time.perf_counter() - t0
        done = [r for r in reqs if r.state == "done"]
        ttfts = np.asarray([r.ttft_s for r in done
                            if r.ttft_s is not None])
        steady = None
        if ts_dir is not None:
            seg = steady_state_percentiles(ts_dir, "serve_ttft_seconds")
            if seg and seg.get("p50") is not None:
                steady = seg
        s = eng.summary()
        out = {
            "wall_s": round(wall, 2),
            "completed": len(done),
            "prefilled_tokens": int(s.get("prefilled_tokens", 0)),
            "max_prefill_tokens_step":
                int(s.get("max_prefill_tokens_step", 0)),
            "prefix_hits": int(s.get("prefix_hits", 0)),
            "prefix_hit_tokens": int(s.get("prefix_hit_tokens", 0)),
            "prefix_hit_rate": float(s.get("prefix_hit_rate", 0.0)),
            "ttft_p50_ms": round(
                float(np.percentile(ttfts, 50)) * 1e3, 2)
                if ttfts.size else None,
            "ttft_p99_ms": round(
                float(np.percentile(ttfts, 99)) * 1e3, 2)
                if ttfts.size else None,
            "latency_source": "request_stamps",
        }
        if steady is not None:
            out["ttft_p50_ms"] = round(steady["p50"] * 1e3, 3)
            out["ttft_p99_ms"] = round(steady["p99"] * 1e3, 3)
            out["latency_source"] = "steady_state_windows"
        return out

    on = run(pool)
    result["sharing_on"] = on
    if progress is not None:
        progress(dict(result))
    off = run(0)
    result["sharing_off"] = off
    saved = off["prefilled_tokens"] - on["prefilled_tokens"]
    result.update({
        "prefix_hit_rate": on["prefix_hit_rate"],
        "prefilled_tokens_saved": saved,
        "ttft_p50_ms": on["ttft_p50_ms"],
        "ttft_p99_ms": on["ttft_p99_ms"],
        "value": round(off["prefilled_tokens"]
                       / max(1, on["prefilled_tokens"]), 2),
        "unit": "prefill_reduction_x",
    })
    if progress is not None:
        progress(dict(result))
    return result


def _leg_fleet(smoke: bool) -> dict:
    """Leg: the kill -9 failover drill on the multi-replica serving
    plane (torchpruner_tpu.fleet) — 3 subprocess replicas under
    open-loop Poisson load, one SIGKILLed mid-stream; the journaled
    queue must redrive to the survivors with zero accepted-request
    loss and every completed request bit-identical to solo decode
    (--verify).  Value = drill wall seconds; the real products are the
    failover/redrive counters and the zero-loss invariant.  Always a
    CPU subprocess drill: N replicas sharing one chip would measure
    contention, not failover."""
    import json as _json
    import subprocess
    import tempfile

    n = 12 if smoke else 24
    fleet_dir = tempfile.mkdtemp(prefix="bench_fleet_")
    t0 = time.perf_counter()
    r = subprocess.run(
        [sys.executable, "-m", "torchpruner_tpu", "fleet", "llama_tiny",
         "--cpu", "--replicas", "3", "--slots", "2", "--max-len", "96",
         "--synthetic", str(n), "--rate", "3.0", "--verify",
         "--prompt-lens", "4,8", "--max-new", "8,12",
         "--fleet-dir", fleet_dir,
         "--chaos", '{"kill_replica_at_step": 5}'],
        capture_output=True, text=True, timeout=900)
    wall = time.perf_counter() - t0
    if r.returncode != 0:
        raise RuntimeError(
            f"fleet drill exited {r.returncode}: {r.stderr[-500:]}")
    s = _json.loads([l for l in r.stdout.splitlines()
                     if l.startswith("{")][-1])
    assert s["lost"] == 0 and s["verify_mismatches"] == 0, s
    return {
        "value": round(wall, 2),
        "unit": "s (kill -9 failover drill wall)",
        "requests": s["requests"],
        "completed": s["completed"],
        "failovers": s["failovers"],
        "redrives": s["redrives"],
        "shed": s["shed"],
        "verify_mismatches": s["verify_mismatches"],
        "killed": s["killed"],
        # distributed-tracing verdicts: cross-process waterfall count
        # and the top-2 TTFT stage contributors under drill load
        "traces_cross_process": s.get("traces_cross_process"),
        "ttft_budget_top2": s.get("ttft_budget_top2"),
        "ttft_recon_pct": s.get("ttft_recon_pct"),
        # telemetry-plane verdicts: per-process time-series merged onto
        # the router clock, and the burn-rate alert count (must be 0 —
        # this drill plants a kill, not an SLO breach)
        "ts_streams": s.get("ts_streams"),
        "ts_windows": s.get("ts_windows"),
        "slo_burn_alerts": s.get("slo_burn_alerts"),
        # incident-correlation verdicts (obs.incident): a kill drill
        # plants no SLO breach, so the false-positive contract is
        # incidents == 0
        "incidents": s.get("incidents"),
        "anomalies": s.get("anomalies"),
    }


def _leg_resilience(smoke: bool) -> dict:
    """Leg: chaos drill — every resilience recovery path exercised and
    timed on the digits smoke preset (torchpruner_tpu.resilience):

    1. NaN-grad injection under the compiled non-finite guard (in
       process): the poisoned step must be skipped, the run must finish.
    2. Deterministic SIGKILL mid-retrain + manifest resume (subprocess,
       CPU): measures the preemption tax — wall-clock of die+resume over
       an uninterrupted run.
    3. Corrupt-checkpoint detection: flipped bytes must surface as
       CheckpointCorruptError (digest verification time included).

    Value = total drill seconds; the real products are the recovery
    counters and the resume_overhead_s ratio."""
    import shutil
    import tempfile

    import numpy as np

    from torchpruner_tpu import obs
    from torchpruner_tpu.checkpoint import (
        CheckpointCorruptError,
        restore_checkpoint,
    )
    from torchpruner_tpu.experiments.train_model import run_train
    from torchpruner_tpu.resilience.chaos import corrupt_checkpoint_bytes
    from torchpruner_tpu.utils.config import ExperimentConfig

    root = tempfile.mkdtemp(prefix="bench_resilience_")

    def cfg(run_dir, chaos=None):
        return ExperimentConfig(
            name="bench_resilience", model="digits_fc_tiny",
            dataset="digits_flat", experiment="train",
            epochs=1 if smoke else 2, batch_size=32, eval_batch_size=64,
            lr=0.05, run_dir=run_dir, checkpoint_every_steps=10,
            guard_nonfinite=True, chaos=chaos or {},
            log_path=os.path.join(run_dir, "log.csv"),
        )

    t_total = time.perf_counter()
    out: dict = {"unit": "s"}
    try:
        # 1. NaN injection recovered in-process
        t0 = time.perf_counter()
        _, hist = run_train(cfg(os.path.join(root, "nan"),
                                chaos={"nan_at_step": 5}), verbose=False)
        out["nan_leg_s"] = round(time.perf_counter() - t0, 3)
        out["nan_skips"] = int(
            obs.counter_value("resilience_nan_skips_total"))
        assert hist and np.isfinite(hist[-1]["test_loss"]), \
            "nan-injected run did not recover"

        # 2. SIGKILL + resume (subprocess; CPU for hermeticity)
        if not smoke:
            repo = os.path.dirname(os.path.abspath(__file__))
            cfg_path = os.path.join(root, "cfg.json")
            kill_dir = os.path.join(root, "kill")
            cfg(kill_dir).to_json(cfg_path)
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       PYTHONPATH=repo + os.pathsep
                       + os.environ.get("PYTHONPATH", ""))

            def cli(*extra):
                return subprocess.run(
                    [sys.executable, "-m", "torchpruner_tpu",
                     "--config", cfg_path, "--cpu", "--resume", kill_dir,
                     "--checkpoint-every", "10", "--no-obs", *extra],
                    capture_output=True, text=True, env=env, cwd=repo,
                    timeout=420,
                )

            t0 = time.perf_counter()
            ref = cli()  # uninterrupted timing baseline (fresh dir later)
            shutil.rmtree(kill_dir, ignore_errors=True)
            base_s = time.perf_counter() - t0
            assert ref.returncode == 0, ref.stderr[-800:]
            t0 = time.perf_counter()
            killed = cli("--chaos", '{"kill_at_step": 20}')
            assert killed.returncode == -9, killed.returncode
            resumed = cli()
            die_resume_s = time.perf_counter() - t0
            assert resumed.returncode == 0, resumed.stderr[-800:]
            out["kill_resume_s"] = round(die_resume_s, 3)
            out["uninterrupted_s"] = round(base_s, 3)
            out["resume_overhead_s"] = round(die_resume_s - base_s, 3)

        # 3. corrupt-checkpoint detection via digest
        t0 = time.perf_counter()
        nan_dir = os.path.join(root, "nan")
        import json as _json

        man = _json.load(open(os.path.join(nan_dir, "manifest.json")))
        ckpt = os.path.join(nan_dir, man["checkpoint"])
        restore_checkpoint(ckpt)  # intact
        assert corrupt_checkpoint_bytes(ckpt, force=True)
        try:
            restore_checkpoint(ckpt)
            raise AssertionError("corruption not detected")
        except CheckpointCorruptError:
            pass
        out["corrupt_detect_s"] = round(time.perf_counter() - t0, 3)

        h = obs.get().metrics.get("checkpoint_write_seconds") \
            if obs.get() else None
        if h is not None and h.count:
            out["checkpoint_write_s_mean"] = round(h.mean, 4)
        out["value"] = round(time.perf_counter() - t_total, 3)
        return out
    finally:
        # the in-process run installed a PROCESS-GLOBAL chaos config; a
        # leg failure before its injection fires would otherwise leave
        # it armed to NaN-poison a later leg's step 5.  disable() (not
        # configure({})) so a TORCHPRUNER_CHAOS env var can't re-arm.
        from torchpruner_tpu.resilience import chaos as _chaos_mod

        _chaos_mod.disable()
        shutil.rmtree(root, ignore_errors=True)


def _leg_zero(smoke: bool) -> dict:
    """Leg: ZeRO-style cross-replica weight-update sharding A/B
    (``ShardedTrainer(zero=True)`` vs replicated) on the vgg16/llama
    train shapes, plus the widened batch sweep the freed optimizer HBM
    buys (experiments/zero_bench.py).  Needs >= 2 devices for a data
    axis; a single-device run (the CPU fallback box) delegates to a
    subprocess with 8 virtual host devices so the transform is still
    exercised and parity-checked — clearly labelled, because virtual-
    device collectives share one core and the ms numbers are not a
    speedup claim (the HBM ratio IS meaningful there)."""
    import jax

    if jax.device_count() >= 2:
        from torchpruner_tpu.experiments import zero_bench

        out = zero_bench.run(smoke=smoke)
        out["value"] = out.get("vgg", {}).get("ms")
        out["unit"] = "ms/step"
        return out
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                   + " --xla_force_host_platform_device_count=8").strip(),
    )
    # CPU is always smoke-sized: the full vgg16/mfu_llama A/B is TPU work
    with tempfile.TemporaryDirectory() as td:
        out_path = os.path.join(td, "zero_bench.json")
        proc = subprocess.run(
            [sys.executable, "-m", "torchpruner_tpu.experiments.zero_bench",
             "--smoke", "--cpu", "--devices", "8", "--out", out_path],
            capture_output=True, text=True, timeout=900, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        # read the --out file, not stdout: a stray warning line from the
        # child would break a whole-stdout json.loads
        if proc.returncode != 0 or not os.path.exists(out_path):
            raise RuntimeError(
                f"zero_bench child failed rc={proc.returncode}: "
                f"{proc.stderr[-400:]}"
            )
        with open(out_path) as f:
            out = json.load(f)
    out["platform"] = "cpu_virtual8"
    out["value"] = out.get("vgg", {}).get("ms")
    out["unit"] = "ms/step"
    return out


def _leg_plan(smoke: bool) -> dict:
    """Leg: the auto-parallelism planner (analysis/planner.py) over the
    vgg16 recipe — the zero-to-ranked-table wall time, candidate/
    feasible counts, and the winner's predicted margin over the
    hand-written preset config.  On TPU the top-2 candidates get short
    measured probes (the drift column the capture script's staged MFU
    assertion reads); the CPU leg stays static (probe drift against
    order-of-magnitude CPU constants gates everything, which is
    signal-free).  Search cost is the point of this leg: pricing the
    whole space must stay cheap enough to run before every expensive
    configuration decision."""
    import jax

    from torchpruner_tpu.analysis import planner
    from torchpruner_tpu.experiments.presets import get_preset

    on_tpu = jax.devices()[0].platform == "tpu"
    # the vgg16 recipe is the MFU-plateau target (ROADMAP item 3); the
    # smoke variant keeps the identical search shape CPU-sized
    cfg = get_preset("vgg16_digits32_layerwise", smoke=smoke or not on_tpu)
    t0 = time.perf_counter()
    plan = planner.plan_auto(
        cfg, n_devices=len(jax.devices()),
        probe_top=2 if on_tpu else 0, probe_steps=8,
    )
    wall = time.perf_counter() - t0
    by_label = {c["label"]: c for c in plan["candidates"]}
    winner = by_label.get(plan["winner"] or "")
    out = {
        "value": round(wall, 3),
        "unit": "s (search wall)",
        "config": plan["config"],
        "n_devices": plan["n_devices_target"],
        "candidates": len(plan["candidates"]),
        "feasible": len(plan["ranked"]),
        "winner": plan["winner"],
        "baseline": plan["baseline"],
        "margin_over_baseline_pct": plan["margin_over_baseline_pct"],
        "margin_over_runner_up_pct": plan["margin_over_runner_up_pct"],
    }
    if winner:
        out["winner_predicted_step_ms"] = winner["predicted"]["step_ms"]
        out["winner_bound"] = winner["predicted"]["bound"]
        out["winner_hbm_gib_per_chip"] = round(
            winner["hbm"]["watermark_bytes_per_chip"] / 2 ** 30, 4)
        if winner.get("probe"):
            out["winner_probe"] = winner["probe"]
    excluded = [c for c in plan["candidates"] if c["excluded_by"]]
    if excluded:
        out["excluded"] = {c["label"]: c["excluded_by"] for c in excluded}
    try:
        from torchpruner_tpu import obs

        obs.gauge_set("plan_search_wall_s", wall,
                      help="planner: full search wall time (s)")
    except Exception:  # noqa: BLE001
        pass
    return out


def _leg_search(smoke: bool) -> dict:
    """Leg: the Pareto sparsity-search campaign driver (search/) on the
    digits_smoke grid — zero-to-frontier wall time, candidate/excluded/
    early-stopped counts, and the frontier's best-accuracy-at-FLOPs
    buckets.  The leg measures the CAMPAIGN machinery (pre-pricing,
    concurrent workers, dominance early-stop, frontier assembly), not
    any single trial: its wall is what 'run the experiment campaign'
    costs end to end on this host."""
    import shutil
    import tempfile

    from torchpruner_tpu.search.driver import run_campaign
    from torchpruner_tpu.search.grid import digits_smoke

    spec = digits_smoke()
    campaign_dir = tempfile.mkdtemp(prefix="bench_search_")
    t0 = time.perf_counter()
    try:
        fr = run_campaign(spec, campaign_dir, cpu=True, verbose=False)
    finally:
        shutil.rmtree(campaign_dir, ignore_errors=True)
    wall = time.perf_counter() - t0
    c = fr["counts"]
    out = {
        "value": round(wall, 3),
        "unit": "s (campaign wall, zero to frontier)",
        "campaign": fr["campaign"],
        "trials": c["trials"],
        "completed": c["completed"],
        "non_dominated": c["non_dominated"],
        "early_stopped": c["early_stopped"],
        "excluded_by_pricing": c["excluded"],
        "failed": c["failed"],
        "frontier_digest": fr["frontier_digest"][:12],
        "buckets": dict(fr["buckets"]),
    }
    accs = [p["accuracy"] for p in fr["points"]
            if p.get("accuracy") is not None]
    if accs:
        out["best_acc"] = max(accs)
    try:
        from torchpruner_tpu import obs

        obs.gauge_set("search_campaign_wall_s", wall,
                      help="search: digits_smoke campaign wall (s)")
    except Exception:  # noqa: BLE001
        pass
    return out


def _leg_ok(legs: dict, name: str) -> bool:
    return (name in legs and "error" not in legs[name]
            and "skipped" not in legs[name]
            and "in_progress" not in legs[name])


def _assemble(legs: dict, platform: str, device_kind, cache_dir,
              smoke: bool) -> dict:
    """Build the headline result record from whatever legs exist so far.

    Shared by the final return AND the per-leg streamed snapshots, so
    every snapshot is a complete, driver-parseable result on its own.
    The sweep headline is named ``..._digits32_...`` because the measured
    dataset differs from the reference's CIFAR-10 (advisor round-3: the
    cross-dataset caveat must ride in the metric itself, not only in
    ``protocol_delta``).
    """
    if _leg_ok(legs, "vgg16_robustness") and not smoke:
        head_name = "vgg16_layerwise_sweep_digits32_wall_clock"
        head = legs["vgg16_robustness"]
    elif _leg_ok(legs, "mnist_prune"):
        head_name = "mnist_fc_shapley_prune_wall_clock"
        head = legs["mnist_prune"]
    else:
        null = _null_result()
        head_name = null.pop("metric")
        head = null
    out = {
        "metric": head_name,
        "value": head["value"],
        "unit": head["unit"],
        "vs_baseline": head.get("vs_baseline"),
        "platform": platform,
        "device_kind": device_kind,
        "compilation_cache": cache_dir,
        "legs": legs,
    }
    if _leg_ok(legs, "vgg16_train"):
        out["mfu"] = legs["vgg16_train"]["mfu"]
        out["img_per_s_per_chip"] = legs["vgg16_train"]["img_per_s_per_chip"]
    try:
        from torchpruner_tpu import obs

        session = obs.get()
        if session is not None:
            out["obs_phases"] = {
                k: {"total_s": round(v["total_s"], 3), "calls": v["calls"],
                    "compile_s": round(v["compile_s"], 3),
                    "compile_count": int(v["compile_count"])}
                for k, v in session.tracer.phase_summary().items()
            }
    except Exception:  # telemetry must never break a bench snapshot
        pass
    return out


def main() -> dict:
    if "--cpu" in sys.argv:
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    smoke = "--smoke" in sys.argv  # tiny config to validate the path on CPU
    cache_dir = None
    # same spelling as the CLI's flag, with the short form as an alias
    if not ({"--no-cache", "--no-compilation-cache"} & set(sys.argv)):
        # persistent XLA compilation cache: repeated shapes (re-runs, config
        # sweeps, resume-after-preemption) skip compilation entirely — the
        # dominant cost of the small workloads.  compile_s in the train leg
        # still reports what this run actually paid.
        from torchpruner_tpu.utils.compilation_cache import (
            enable_persistent_cache,
        )

        cache_dir = enable_persistent_cache()
    platform = jax.devices()[0].platform
    device_kind = getattr(jax.devices()[0], "device_kind", None)
    on_tpu = platform == "tpu"
    # runtime telemetry: every leg runs under an obs span, so the BENCH
    # rows carry wall/compile accounting per leg (and the full event
    # stream lands in $BENCH_OBS_DIR when set).  Telemetry must never
    # break a bench run — an unwritable BENCH_OBS_DIR degrades to
    # in-memory-only tracking.
    from torchpruner_tpu import obs

    try:
        obs.configure(os.environ.get("BENCH_OBS_DIR") or None)
    except Exception as e:  # noqa: BLE001
        print(f"[bench] obs dir unusable ({e}); in-memory telemetry only",
              file=sys.stderr, flush=True)
        try:
            obs.configure(None)
        except Exception:  # noqa: BLE001
            pass
    legs: dict = {}
    commit = _git_commit()  # once — it cannot change mid-run
    # absolute deadline handed down by the orchestrator (epoch seconds);
    # absent for manual --run invocations → no leg is ever skipped
    deadline = float(os.environ["BENCH_DEADLINE_TS"]) \
        if "BENCH_DEADLINE_TS" in os.environ else None

    def snapshot():
        """Stream the best-available full result as ONE stdout JSON line
        (the orchestrator forwards it; a driver kill keeps the last one)
        and persist the salvage record.  Never aborts remaining legs."""
        if smoke:
            return
        try:
            snap = _assemble(legs, platform, device_kind, cache_dir, smoke)
            snap["stream"] = "in_progress"
            print(json.dumps(snap), flush=True)
        except Exception:  # noqa: BLE001
            pass
        try:  # atomic replace so a kill mid-write can't tear the record
            blob = json.dumps({
                "platform": platform,
                "git_commit": commit,
                "written_at": time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                "legs": legs,
            }, indent=1)
            tmp = PARTIAL_PATH + ".tmp"
            with open(tmp, "w") as f:
                f.write(blob)
            os.replace(tmp, PARTIAL_PATH)
        except Exception:  # noqa: BLE001
            pass

    def run_leg(name, fn):
        # budget guard: starting a leg that cannot finish before the
        # orchestrator's deadline wastes the time a finishable leg could
        # have used, and gets killed with nothing to show (round-3
        # postmortem).  Coarse estimates, deliberately pessimistic.
        if deadline is not None and not smoke:
            est = _LEG_EST_S.get(name, (0, 0))[0 if on_tpu else 1]
            remaining = deadline - time.time()
            if est > remaining:
                legs[name] = {"skipped": f"budget: ~{est}s estimated > "
                                         f"{remaining:.0f}s remaining"}
                print(f"[bench] {name} skipped (budget)", file=sys.stderr,
                      flush=True)
                snapshot()
                return
        # fault isolation: one leg's failure must not destroy the other
        # measurements (round-2 postmortem: a Pallas lowering error in the
        # flash leg crashed the whole TPU attempt and forced CPU fallback)
        print(f"[bench] {name} starting", file=sys.stderr, flush=True)
        t0 = time.perf_counter()
        kw = {}
        if "progress" in inspect.signature(fn).parameters:
            # a long leg checkpoints itself: each call replaces the leg's
            # entry with an in_progress partial and streams a snapshot,
            # so a kill mid-sweep keeps the finished layers
            def _progress(partial: dict, _name=name):
                legs[_name] = dict(partial, in_progress=True)
                snapshot()
            kw["progress"] = _progress
        from torchpruner_tpu import obs

        try:
            with obs.span(f"leg:{name}") as leg_span:
                legs[name] = fn(smoke, **kw)
            if isinstance(legs[name], dict) and leg_span is not None:
                # attach the obs accounting so every BENCH row carries its
                # phase timings and compile bill (span ids join with the
                # child phases in the events stream / obs_phases block)
                legs[name]["obs"] = {
                    "span": leg_span.id,
                    "wall_s": round(leg_span.dur_s, 3),
                    "compile_s": round(leg_span.compile_s, 3),
                    "compile_count": leg_span.compile_count,
                    "trace_count": leg_span.trace_count,
                }
        except Exception as e:  # noqa: BLE001 - diagnostic, re-raised as data
            import traceback

            err = {
                "error": f"{type(e).__name__}: {e}"[:500],
                "traceback_tail": traceback.format_exc()[-500:],
            }
            prev = legs.get(name)
            if isinstance(prev, dict) and prev.get("in_progress"):
                # a crash late in a checkpointing leg must not discard the
                # finished layers' data — merge the error into the partial
                # (and drop the still-running flag: this entry is final)
                err = {**prev, **err}
                err.pop("in_progress", None)
            legs[name] = err
        # stderr progress so an orchestrator timeout still documents which
        # legs completed and where the time went (round-2 postmortem: a
        # 900 s TPU timeout left zero evidence of the slow leg)
        print(
            f"[bench] {name} done in {time.perf_counter() - t0:.1f}s",
            file=sys.stderr, flush=True,
        )
        snapshot()

    run_leg("mnist_prune", _leg_mnist)
    # chaos drill: CPU-cheap on every platform, and the recovery paths it
    # exercises (kill/resume, NaN skip, digest verify) are exactly what a
    # preemptible TPU attempt of the legs below depends on
    run_leg("resilience", _leg_resilience)
    # planner search: cheap on every platform (static pricing; probes
    # only on TPU) and the config it proposes frames the train legs below
    run_leg("plan", _leg_plan)
    # sparsity-search campaign: the digits_smoke grid end to end
    # (pre-pricing -> concurrent prune-retrain workers -> dominance
    # early-stop -> frontier artifact); CPU-cheap, and the campaign wall
    # is the number ROADMAP item 4's fleet scheduling starts from
    run_leg("search", _leg_search)
    if on_tpu or smoke or "--all-legs" in sys.argv:
        # cheap legs first, the long full-sweep leg last: if the child is
        # killed mid-run, the streamed snapshots hold the most
        # measurements per minute spent
        run_leg("mfu_llama", _leg_mfu_llama)
        run_leg("vgg16_train", _leg_vgg_train)
        run_leg("zero", _leg_zero)
        run_leg("flash_attention", _leg_flash_attention)
        run_leg("blocksparse", _leg_blocksparse)
        run_leg("llama_decode", _leg_llama_decode)
        run_leg("serve", _leg_serve)
        if "--prefix-heavy" in sys.argv:
            # Serve v2 opt-in: the prefix-sharing on/off A-B costs a
            # second engine's compiles, so it doesn't ride every run
            run_leg("serve_prefix", _leg_serve_prefix)
        # fleet failover drill: CPU subprocesses on every platform (the
        # drill measures the serving PLANE's robustness, not the chip)
        run_leg("fleet", _leg_fleet)
        run_leg("vgg16_robustness", _leg_vgg_robustness)
    else:
        # CPU fallback: the VGG legs are TPU-sized, but decode on
        # llama_tiny is CPU-sized — keep it so every round's artifact has
        # a decode number on SOME platform (round-2 gap); the serve leg
        # (continuous batching on the same tiny model) likewise
        run_leg("llama_decode", _leg_llama_decode)
        run_leg("serve", _leg_serve)
        if "--prefix-heavy" in sys.argv:
            run_leg("serve_prefix", _leg_serve_prefix)
        run_leg("fleet", _leg_fleet)

    # assemble BEFORE shutdown (it reads the live session's phase
    # summary), then flush the exporters — with BENCH_OBS_DIR set this
    # writes the run_summary event + metrics.prom and unregisters the
    # compile listener
    result = _assemble(legs, platform, device_kind, cache_dir, smoke)
    try:
        obs.shutdown()
    except Exception:  # noqa: BLE001
        pass
    _attach_obs_diff(result, platform)
    return result


def _attach_obs_diff(result: dict, platform: str) -> None:
    """Auto-diff this run's obs report (BENCH_OBS_DIR) against the newest
    committed ``results/obs_report_*<platform>*.json`` and attach the
    outcome — scalar deltas + any BENCH_GATES violations — to the result
    record.  Informational only (a bench must report regressions, not
    abort on them); ``BENCH_SAVE_OBS_REPORT=1`` additionally copies the
    fresh report into results/ as the next baseline.  Never raises."""
    obs_dir = os.environ.get("BENCH_OBS_DIR")
    if not obs_dir:
        return
    try:
        from torchpruner_tpu.obs.report import (
            check_gates,
            diff_runs,
            load_run,
            newest_report,
        )

        current = load_run(obs_dir)
        # baseline BEFORE save: saving first would make newest_report
        # return the just-written file and diff the run against itself
        baseline = newest_report(RESULTS_DIR, match=platform)
        if os.environ.get("BENCH_SAVE_OBS_REPORT"):
            stamp = time.strftime("%Y-%m-%d_%H%M", time.gmtime())
            dst = os.path.join(
                RESULTS_DIR, f"obs_report_{platform}_{stamp}.json")
            import shutil

            os.makedirs(RESULTS_DIR, exist_ok=True)
            shutil.copyfile(os.path.join(obs_dir, "report.json"), dst)
            result["obs_report_saved"] = dst
        if baseline is None:
            result["obs_diff"] = {"baseline": None,
                                  "note": "no committed obs_report_* "
                                          f"for {platform} in results/"}
            return
        with open(baseline) as f:
            base = json.load(f)
        d = diff_runs(base, current)
        violations = check_gates(d, BENCH_GATES)
        result["obs_diff"] = {
            "baseline": os.path.basename(baseline),
            "scalars": d["scalars"],
            "violations": violations,
        }
        for v in violations:
            print(f"[bench] obs-diff gate violation [{v['gate']}]: "
                  f"{v['detail']}", file=sys.stderr, flush=True)
    except Exception as e:  # noqa: BLE001 - telemetry never fails a bench
        result["obs_diff"] = {"error": f"{type(e).__name__}: {e}"[:300]}


def _stream_child(cmd: list[str], timeout_s: float, enrich) -> tuple:
    """Run the measurement child, forwarding every JSON snapshot line from
    its stdout to OUR stdout the moment it appears (after ``enrich``).

    This is the round-3 fix: ``subprocess.run(capture_output=True)``
    buffers the child's output inside the orchestrator, so a driver kill
    of the orchestrator discards everything.  Streaming means the driver's
    pipe already holds every finished leg's snapshot when the kill lands.
    Child stderr is teed: live to our stderr (progress reaches the
    driver's tail) AND into a bounded tail buffer for the ``attempts``
    record.  Returns ``(rc, last_snapshot_or_None, stderr_tail)``.
    """
    import threading
    from collections import deque

    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    timed_out = threading.Event()

    def _kill():
        timed_out.set()
        proc.kill()

    timer = threading.Timer(timeout_s, _kill)
    timer.start()
    err_tail: deque = deque(maxlen=12)

    def _pump_stderr():
        for line in proc.stderr:
            sys.stderr.write(line)
            sys.stderr.flush()
            err_tail.append(line[:400])

    pump = threading.Thread(target=_pump_stderr, daemon=True)
    pump.start()
    last = None
    try:
        for line in proc.stdout:
            line = line.strip()
            if not line:
                continue
            try:
                cand = json.loads(line)
            except (json.JSONDecodeError, ValueError):
                continue
            if isinstance(cand, dict) and "metric" in cand:
                last = enrich(cand)
                print(json.dumps(last), flush=True)
    finally:
        timer.cancel()
    rc = proc.wait()
    pump.join(timeout=5)
    if timed_out.is_set():
        rc = -1
    return rc, last, "".join(err_tail)[-1500:]


def orchestrate() -> dict:
    """Run the measurement in a child process with preflight + streaming
    + CPU fallback, inside a total wall-clock budget.

    Attempt 1: default platform (TPU when available, and only when a
    capped device probe vouches for the tunnel). Attempt 2: ``--cpu`` so a
    broken TPU backend still yields a real measurement, labelled with the
    forced platform. The fallback is the flag (an in-process
    ``jax.config.update("jax_platforms", "cpu")``), NOT the
    ``JAX_PLATFORMS`` env var: with the experimental axon plugin installed
    the env var still blocks in plugin discovery, while the config update
    cleanly skips it (measured on the round-2 box: env var hangs > 120 s,
    config update returns in 16 ms). Always returns a dict — and has
    already PRINTED every intermediate snapshot, so even `kill -9` at a
    random moment leaves a parseable stdout.
    """
    t_start = time.time()
    deadline = t_start + TOTAL_BUDGET_S
    # (1) an immediately-parseable line: whatever happens next (hung
    # probe, driver kill, plugin crash), the driver's parser finds a JSON
    # record carrying the cached TPU evidence instead of `parsed: null`
    boot = _null_result(
        stream="starting",
        note="streaming bench: the LAST JSON line on stdout is the result",
    )
    if "--smoke" not in sys.argv:
        _attach_last_tpu(boot)
    print(json.dumps(boot), flush=True)

    passthrough = [a for a in sys.argv[1:] if a != "--run"]
    cmd = [sys.executable, os.path.abspath(__file__), "--run", *passthrough]
    attempts: list[dict] = []
    best_partial: dict | None = None  # parseable result, null headline
    plans = [False, True]  # forced-cpu flag per attempt
    if "--cpu" not in sys.argv:
        # (2) budget-aware pre-flight: a hung TPU tunnel parks backend
        # init in retry-sleep for the whole child timeout (measured: 40
        # min lost per attempt during a round-2 outage), and round 3
        # showed the opposite failure — long probe sleeps ate the
        # driver's entire budget before the fallback could run.  Round 4
        # failed a third way: 2 back-to-back hung probes gave up on a
        # tunnel that answered later the same day.  So: probe in a
        # RETRY WINDOW sized off the remaining budget — keep probing as
        # long as a success would still leave room for a TPU attempt
        # (tpu_min_window) plus the CPU-fallback reserve.  Default
        # budget (1200 s): ~5 min of probing; deep runs
        # (BENCH_TOTAL_BUDGET_S=10800): ~2.3 h of probing.
        probe_interval = float(os.environ.get("BENCH_PROBE_INTERVAL_S",
                                              "30"))
        probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "75"))
        tpu_min_window = min(1800.0, 0.25 * TOTAL_BUDGET_S)
        probe_window = float(os.environ.get(
            "BENCH_PROBE_WINDOW_S",
            max(180.0, (deadline - time.time()) - CPU_RESERVE_S
                - tpu_min_window)))
        max_probes = os.environ.get("BENCH_PROBE_RETRIES")
        max_probes = 1 + int(max_probes) if max_probes else None
        probe_deadline = time.time() + probe_window
        probe_ok, probe_msg, n_probes = False, "", 0
        while True:
            n_probes += 1
            try:
                probe = subprocess.run(
                    [sys.executable, "-c", "import jax; jax.devices()"],
                    capture_output=True, text=True, timeout=probe_timeout,
                )
                probe_ok = probe.returncode == 0
                probe_msg = (probe.stderr or "").strip()[-300:]
            except subprocess.TimeoutExpired as e:
                probe_ok = False
                probe_msg = (f"device probe hung >{probe_timeout:.0f}s: "
                             f"{(e.stderr or '')[-200:]}")
            if probe_ok:
                break
            print(f"[bench] preflight probe {n_probes} failed "
                  f"({max(0.0, probe_deadline - time.time()):.0f}s of "
                  f"window left)", file=sys.stderr, flush=True)
            if (max_probes and n_probes >= max_probes) or \
                    time.time() + probe_interval + probe_timeout \
                    > probe_deadline:
                break
            time.sleep(probe_interval)
        if not probe_ok:
            attempts.append({
                "attempt": 0,
                "rc": None,
                "forced_platform": None,
                "stderr_tail": f"preflight failed ({n_probes} probes over "
                               f"{probe_window:.0f}s window), "
                               f"skipping TPU attempts: {probe_msg}",
            })
            plans = [True]

    def enrich(cand: dict) -> dict:
        # every forwarded snapshot is self-sufficient: non-TPU snapshots
        # carry the cached TPU evidence; any snapshot after a timed-out
        # TPU attempt carries that attempt's finished legs
        if "--smoke" not in sys.argv and cand.get("platform") != "tpu":
            _attach_last_tpu(cand)
        if (best_partial is not None
                and best_partial.get("platform") == "tpu"
                and cand.get("platform") != "tpu"):
            cand["tpu_partial"] = best_partial
        if attempts:
            cand["attempts"] = attempts
        return cand

    external_deadline = os.environ.get("BENCH_DEADLINE_TS")
    for i, force_cpu in enumerate(plans):
        remaining = deadline - time.time()
        if remaining < 60:
            attempts.append({"attempt": len(attempts) + 1, "rc": None,
                             "forced_platform": "cpu" if force_cpu else None,
                             "stderr_tail": "skipped: total budget exhausted"})
            continue
        # a TPU attempt must leave the CPU fallback room to produce its
        # headline: a child hung mid-leg is killed CPU_RESERVE_S early
        # rather than starving the fallback (review finding, round 4)
        fallback_pending = i + 1 < len(plans)
        child_timeout = (max(120.0, remaining - CPU_RESERVE_S)
                         if fallback_pending else remaining + 60)
        attempt_cmd = cmd + (["--cpu"] if force_cpu and "--cpu" not in cmd
                             else [])
        os.environ["BENCH_DEADLINE_TS"] = external_deadline or \
            f"{t_start + TOTAL_BUDGET_S - (CPU_RESERVE_S if fallback_pending else 0):.0f}"
        rc, result, err_tail = _stream_child(attempt_cmd, child_timeout,
                                             enrich)
        if result is None and rc != 0:
            # a killed child that never got a snapshot line out — fall
            # back to the on-disk partial record (only if written by THIS
            # run)
            try:
                if os.path.getmtime(PARTIAL_PATH) > t_start:
                    with open(PARTIAL_PATH) as f:
                        part = json.load(f)
                    result = _null_result(
                        platform=part.get("platform"),
                        salvaged_partial=True,
                        git_commit=part.get("git_commit"),
                        written_at=part.get("written_at"),
                        legs=part.get("legs", {}),
                    )
                    mn = part.get("legs", {}).get("mnist_prune")
                    if isinstance(mn, dict) and "error" not in mn \
                            and mn.get("value") is not None:
                        result["value"] = mn["value"]
                        result["vs_baseline"] = mn.get("vs_baseline")
            except (OSError, json.JSONDecodeError):
                pass
        if rc == 0 and result is not None and result.get("value") is not None:
            result.pop("stream", None)
            if result.get("platform") == "tpu" and "--smoke" not in sys.argv:
                # the PRINTED result must carry previously-cached legs a
                # budget-capped child skipped (e.g. the 15-layer sweep),
                # and its headline must be re-assembled from the merged
                # set — otherwise a fast subset run demotes the recorded
                # headline to the MNIST metric even though a measured
                # sweep sits in the cache (round-4 rehearsal bug)
                merged = _merge_cached_legs(result.get("legs", {}),
                                            replace_errors=False)
                result.update(_assemble(
                    merged, result.get("platform"),
                    result.get("device_kind"),
                    result.get("compilation_cache"), False))
            if attempts:
                result["attempts"] = attempts
            if (best_partial is not None
                    and best_partial.get("platform") == "tpu"
                    and result.get("platform") != "tpu"):
                result["tpu_partial"] = best_partial
            if result.get("platform") == "tpu" and "--smoke" not in sys.argv:
                _write_tpu_cache(result)
            elif "--smoke" not in sys.argv:
                _attach_last_tpu(result)
            return result
        if result is not None:
            # headline leg failed but other legs may carry measurements —
            # keep the attempt with the most successful legs (a later
            # all-error CPU fallback must not clobber a TPU partial)
            def n_ok(r):
                return sum(
                    1 for leg in r.get("legs", {}).values()
                    if isinstance(leg, dict) and "error" not in leg
                    and "skipped" not in leg and "in_progress" not in leg
                )

            if best_partial is None or n_ok(result) > n_ok(best_partial):
                best_partial = result
        attempts.append({
            "attempt": len(attempts) + 1,
            "rc": rc,
            "forced_platform": "cpu" if force_cpu else None,
            "stderr_tail": (f"child killed at {child_timeout:.0f}s: "
                            if rc == -1 else "") + err_tail,
        })
    if best_partial is not None:
        best_partial["error"] = (
            "partial run — child killed before finishing (see "
            "legs/attempts)" if best_partial.get("value") is not None
            else "headline leg failed (see legs/attempts)"
        )
        best_partial["attempts"] = attempts
        best_partial.pop("stream", None)
        if "--smoke" not in sys.argv:
            _attach_last_tpu(best_partial)
        return best_partial
    out = _null_result(
        error="all bench attempts failed (see attempts)",
        attempts=attempts,
    )
    _attach_last_tpu(out)
    return out


def _merge_cached_legs(legs: dict, *, replace_errors: bool = True) -> dict:
    """``legs`` extended with previously-cached TPU legs this run skipped
    or didn't reach (a budget-capped run that skips the 2400 s sweep must
    not erase a previously-captured sweep) — each carried leg labelled
    with the commit/timestamp it was measured at.  Shared by the cache
    writer below and the per-leg capture runner, so a SUBSET capture's
    headline is assembled from the merged set, not just this run's legs.

    ``replace_errors=False`` (the PRINTED-result path) keeps a leg that
    errored THIS run visible instead of papering over the regression
    with a stale cached success; the cache file itself stays
    last-known-good per leg (``True``)."""
    merged = dict(legs)
    try:
        with open(TPU_CACHE) as f:
            old = json.load(f)
        for name, leg in old.get("result", {}).get("legs", {}).items():
            cur = merged.get(name)
            cur_ok = isinstance(cur, dict) and "error" not in cur \
                and "skipped" not in cur
            cur_errored = isinstance(cur, dict) and "error" in cur
            if cur_ok or (cur_errored and not replace_errors) \
                    or not isinstance(leg, dict) or "error" in leg \
                    or "skipped" in leg:
                continue
            merged[name] = dict(leg)
            merged[name].setdefault("carried_from", {
                "git_commit": old.get("git_commit"),
                "measured_at": old.get("measured_at"),
            })
    except (OSError, json.JSONDecodeError):
        pass
    return merged


def _write_tpu_cache(result: dict) -> None:
    """Refresh the last-known-TPU cache with carried-forward legs."""
    merged = dict(result)
    merged["legs"] = _merge_cached_legs(merged.get("legs", {}))
    try:
        with open(TPU_CACHE, "w") as f:
            json.dump({
                "measured_at": time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                "git_commit": _git_commit(),
                "result": merged,
            }, f, indent=1)
    except OSError:
        pass


def _null_result(**extra) -> dict:
    """The parseable no-measurement skeleton (one definition — the
    salvage path, the all-failed path, and main()'s empty-legs headline
    share the metric-name contract)."""
    return {
        "metric": "mnist_fc_shapley_prune_wall_clock",
        "value": None,
        "unit": "s",
        "vs_baseline": None,
        **extra,
    }


def _git_commit() -> str:
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)), timeout=10,
        ).stdout.strip()
    except Exception:  # noqa: BLE001
        return "unknown"


def _attach_last_tpu(result: dict) -> None:
    """Embed the cached last-successful TPU measurement (with its commit
    and timestamp — NOT current numbers) into a non-TPU result."""
    try:
        with open(TPU_CACHE) as f:
            result["last_known_tpu"] = json.load(f)
    except (OSError, json.JSONDecodeError):
        pass


if __name__ == "__main__":
    if "--run" in sys.argv:
        print(json.dumps(main()), flush=True)
    else:
        print(json.dumps(orchestrate()), flush=True)

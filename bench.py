"""Benchmark: the reference's headline prune workload on TPU.

Reproduces the "Pruning Untrained Networks" MNIST experiment end to end
(BASELINE.md: 28 s wall-clock on a CUDA GPU): untrained 784-2024-2024-10 FC
net, Shapley attribution (sv_samples=5) on 1000 validation examples for both
hidden layers (outermost first), pruning all negative-attribution units —
including all JIT compilation and the shape-changing recompile between the
two prune steps.

Prints ONE JSON line:
  {"metric": ..., "value": seconds, "unit": "s", "vs_baseline": 28/seconds}
(vs_baseline > 1 means faster than the reference.)
"""

from __future__ import annotations

import json
import os
import sys
import time

BASELINE_SECONDS = 28.0  # reference wall-clock (BASELINE.md, MNIST FC prune)


def main() -> dict:
    if "--cpu" in sys.argv:
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import numpy as np

    from torchpruner_tpu.attributions import ShapleyAttributionMetric
    from torchpruner_tpu.core.graph import pruning_graph
    from torchpruner_tpu.core.pruner import prune_by_scores
    from torchpruner_tpu.core.segment import init_model
    from torchpruner_tpu.data import load_dataset
    from torchpruner_tpu.models import mnist_fc
    from torchpruner_tpu.utils.flops import param_count
    from torchpruner_tpu.utils.losses import cross_entropy_loss

    smoke = "--smoke" in sys.argv  # tiny config to validate the path on CPU
    if smoke:
        from torchpruner_tpu.models.mlp import fc_net

        model = fc_net(784, hidden=(64, 64))
        n_examples, bs = 64, 32
    else:
        model = mnist_fc()
        n_examples, bs = 1000, 500
    params, state = init_model(model, seed=0)
    val = load_dataset("mnist_flat", "val", n=n_examples, seed=0)
    batches = val.batches(bs)
    # stage data on device once (input pipeline, not the measured prune loop)
    batches = [(jax.numpy.asarray(x), jax.numpy.asarray(y)) for x, y in batches]
    jax.block_until_ready(batches)

    params_before = param_count(params)
    t0 = time.perf_counter()
    targets = [g.target for g in pruning_graph(model)][::-1]  # fc2 then fc1
    for target in targets:
        metric = ShapleyAttributionMetric(
            model, params, batches, cross_entropy_loss, state=state,
            sv_samples=5, seed=0,
        )
        scores = metric.run(target)
        res = prune_by_scores(model, params, target, scores,
                              policy="negative", state=state)
        model, params, state = res.model, res.params, res.state
    jax.block_until_ready(params)
    elapsed = time.perf_counter() - t0

    return {
        "metric": "mnist_fc_shapley_prune_wall_clock",
        "value": round(elapsed, 3),
        "unit": "s",
        "vs_baseline": round(BASELINE_SECONDS / elapsed, 3),
        "platform": jax.devices()[0].platform,
        "params_before": params_before,
        "params_after": param_count(params),
    }


if __name__ == "__main__":
    print(json.dumps(main()))
